// Native scheduler core: ICI topology allocation + node scoring.
//
// Reference analogue: the C++ scheduling substrate in
// src/ray/common/scheduling/ (ResourceSet/FixedPoint arithmetic,
// cluster_resource_scheduler.cc node scoring) and the bundle packing
// policies (src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h).
// TPU-first difference: the hot combinatorial problem here is CONTIGUOUS
// SUB-BOX search on an ICI mesh (STRICT_PACK bundles must form an
// ICI-connected box so in-program collectives never leave ICI) — a
// constraint NCCL-land never had, and one that's O(shapes x origins x
// volume) per allocation. At pod scale (v4-4096: 16x16x16) the pure-Python
// scan is milliseconds-to-seconds; this native core keeps it microseconds.
//
// Flat C ABI for ctypes (no pybind11 in this image). Semantics mirror
// raytpu/core/topology.py exactly: most-compact factorization first
// (min max-dim, then min sum), row-major origin scan, first fit.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <set>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kMaxDim = 8;

struct Topo {
  int ndim = 0;
  int shape[kMaxDim] = {0};
  int strides[kMaxDim] = {0};
  int64_t volume = 0;
  int64_t free_count = 0;
  std::vector<uint8_t> occupied;
};

std::mutex g_mu;
std::unordered_map<int64_t, Topo*> g_topos;
int64_t g_next_id = 1;

int64_t FlatIndex(const Topo& t, const int* coord) {
  int64_t idx = 0;
  for (int i = 0; i < t.ndim; i++) idx += int64_t(coord[i]) * t.strides[i];
  return idx;
}

// All axis-aligned box shapes with the given volume that fit, most compact
// first (min max-dim, then min sum) — matches TpuTopology._box_shapes.
void BoxShapes(const Topo& t, int64_t chips,
               std::vector<std::vector<int>>* out) {
  std::set<std::vector<int>> shapes;
  std::vector<int> dims;
  // recursive factorization without recursion: explicit stack
  struct Frame { int64_t remaining; std::vector<int> dims; };
  std::vector<Frame> stack;
  stack.push_back({chips, {}});
  while (!stack.empty()) {
    Frame f = std::move(stack.back());
    stack.pop_back();
    int axis = int(f.dims.size());
    if (axis == t.ndim - 1) {
      if (f.remaining <= t.shape[t.ndim - 1]) {
        std::vector<int> s = f.dims;
        s.push_back(int(f.remaining));
        shapes.insert(std::move(s));
      }
      continue;
    }
    int64_t cap = std::min<int64_t>(f.remaining, t.shape[axis]);
    for (int64_t d = 1; d <= cap; d++) {
      if (f.remaining % d == 0) {
        std::vector<int> nd = f.dims;
        nd.push_back(int(d));
        stack.push_back({f.remaining / d, std::move(nd)});
      }
    }
  }
  out->assign(shapes.begin(), shapes.end());
  std::sort(out->begin(), out->end(),
            [](const std::vector<int>& a, const std::vector<int>& b) {
              int ma = *std::max_element(a.begin(), a.end());
              int mb = *std::max_element(b.begin(), b.end());
              if (ma != mb) return ma < mb;
              int sa = 0, sb = 0;
              for (int x : a) sa += x;
              for (int x : b) sb += x;
              if (sa != sb) return sa < sb;
              return a < b;
            });
}

// Scan origins row-major; on first fully-free box, claim it and write the
// claimed coordinates (row-major within the box) into out_coords.
bool FindAndClaimBox(Topo& t, const std::vector<int>& dims,
                     int* out_coords) {
  int ndim = t.ndim;
  int origin[kMaxDim] = {0};
  int limit[kMaxDim];
  for (int i = 0; i < ndim; i++) {
    limit[i] = t.shape[i] - dims[i] + 1;
    if (limit[i] <= 0) return false;
  }
  while (true) {
    // Check the box at `origin`.
    bool ok = true;
    int off[kMaxDim] = {0};
    int coord[kMaxDim];
    while (ok) {
      for (int i = 0; i < ndim; i++) coord[i] = origin[i] + off[i];
      if (t.occupied[FlatIndex(t, coord)]) { ok = false; break; }
      // advance off (row-major, last axis fastest)
      int ax = ndim - 1;
      while (ax >= 0) {
        if (++off[ax] < dims[ax]) break;
        off[ax] = 0;
        ax--;
      }
      if (ax < 0) break;  // visited every cell — all free
    }
    if (ok) {
      // Claim + emit coordinates in row-major box order.
      int n = 0;
      std::memset(off, 0, sizeof(off));
      while (true) {
        for (int i = 0; i < ndim; i++) {
          coord[i] = origin[i] + off[i];
          out_coords[n * ndim + i] = coord[i];
        }
        t.occupied[FlatIndex(t, coord)] = 1;
        n++;
        int ax = ndim - 1;
        while (ax >= 0) {
          if (++off[ax] < dims[ax]) break;
          off[ax] = 0;
          ax--;
        }
        if (ax < 0) break;
      }
      t.free_count -= n;
      return true;
    }
    // advance origin (row-major)
    int ax = ndim - 1;
    while (ax >= 0) {
      if (++origin[ax] < limit[ax]) break;
      origin[ax] = 0;
      ax--;
    }
    if (ax < 0) return false;
  }
}

}  // namespace

extern "C" {

int64_t topo_create(const int* shape, int ndim) {
  if (ndim < 1 || ndim > kMaxDim) return -1;
  auto* t = new Topo();
  t->ndim = ndim;
  t->volume = 1;
  for (int i = 0; i < ndim; i++) {
    if (shape[i] < 1) { delete t; return -1; }
    t->shape[i] = shape[i];
    t->volume *= shape[i];
  }
  int64_t stride = 1;
  for (int i = ndim - 1; i >= 0; i--) {
    t->strides[i] = int(stride);
    stride *= t->shape[i];
  }
  t->free_count = t->volume;
  t->occupied.assign(size_t(t->volume), 0);
  std::lock_guard<std::mutex> lock(g_mu);
  int64_t id = g_next_id++;
  g_topos[id] = t;
  return id;
}

void topo_destroy(int64_t id) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_topos.find(id);
  if (it != g_topos.end()) {
    delete it->second;
    g_topos.erase(it);
  }
}

int64_t topo_num_free(int64_t id) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_topos.find(id);
  return it == g_topos.end() ? -1 : it->second->free_count;
}

// Allocate a contiguous box of `chips`. out_coords must hold chips*ndim
// ints. Returns chips on success, 0 if no contiguous box fits, -1 error.
int64_t topo_alloc_subcube(int64_t id, int64_t chips, int* out_coords) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_topos.find(id);
  if (it == g_topos.end() || chips <= 0) return -1;
  Topo& t = *it->second;
  if (chips > t.free_count) return 0;
  std::vector<std::vector<int>> shapes;
  BoxShapes(t, chips, &shapes);
  for (const auto& dims : shapes) {
    if (FindAndClaimBox(t, dims, out_coords)) return chips;
  }
  return 0;
}

// Contiguous if possible, else any free chips (row-major order).
int64_t topo_alloc_any(int64_t id, int64_t chips, int* out_coords) {
  {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_topos.find(id);
    if (it == g_topos.end() || chips <= 0) return -1;
    if (chips > it->second->free_count) return 0;
  }
  int64_t got = topo_alloc_subcube(id, chips, out_coords);
  if (got > 0) return got;
  std::lock_guard<std::mutex> lock(g_mu);
  Topo& t = *g_topos[id];
  int64_t n = 0;
  int coord[kMaxDim] = {0};
  for (int64_t flat = 0; flat < t.volume && n < chips; flat++) {
    if (!t.occupied[flat]) {
      int64_t rem = flat;
      for (int i = 0; i < t.ndim; i++) {
        coord[i] = int(rem / t.strides[i]);
        rem %= t.strides[i];
      }
      for (int i = 0; i < t.ndim; i++) out_coords[n * t.ndim + i] = coord[i];
      t.occupied[flat] = 1;
      n++;
    }
  }
  t.free_count -= n;
  return n;
}

void topo_release(int64_t id, const int* coords, int64_t n) {
  std::lock_guard<std::mutex> lock(g_mu);
  auto it = g_topos.find(id);
  if (it == g_topos.end()) return;
  Topo& t = *it->second;
  for (int64_t k = 0; k < n; k++) {
    int64_t idx = FlatIndex(t, coords + k * t.ndim);
    if (idx >= 0 && idx < t.volume && t.occupied[size_t(idx)]) {
      t.occupied[size_t(idx)] = 0;
      t.free_count++;
    }
  }
}

// Hybrid pack/spread node scoring in one pass (reference:
// hybrid_scheduling_policy.h:50). avail/total: n_nodes x n_res row-major.
// Returns best node index or -1 if none feasible. Utilization = max over
// resources of used/total; pack onto the most-utilized feasible node
// until it crosses spread_threshold, then pick the least-utilized.
int64_t score_nodes(const double* avail, const double* total,
                    int64_t n_nodes, int64_t n_res, const double* request,
                    double spread_threshold) {
  constexpr double kEps = 1e-9;
  int64_t best_pack = -1, best_spread = -1;
  double best_pack_util = -1.0, best_spread_util = 2.0;
  for (int64_t n = 0; n < n_nodes; n++) {
    const double* a = avail + n * n_res;
    const double* tt = total + n * n_res;
    bool feasible = true;
    double util = 0.0;
    for (int64_t r = 0; r < n_res; r++) {
      if (a[r] + kEps < request[r]) { feasible = false; break; }
      if (tt[r] > 0) {
        double u = (tt[r] - a[r]) / tt[r];
        if (u > util) util = u;
      }
    }
    if (!feasible) continue;
    if (util > best_pack_util) { best_pack_util = util; best_pack = n; }
    if (util < best_spread_util) { best_spread_util = util; best_spread = n; }
  }
  if (best_pack < 0) return -1;
  return best_pack_util < spread_threshold ? best_pack : best_spread;
}

}  // extern "C"
