// Shared-memory object store — the plasma equivalent, TPU-host edition.
//
// Reference analogue: src/ray/object_manager/plasma/ (PlasmaStore,
// plasma_allocator.cc, eviction_policy.cc). Design differences, on purpose:
// the reference runs a store *server* thread inside the raylet and clients
// talk to it over a unix socket with fd-passing (plasma/fling.cc). Here the
// store is a *passive* shared-memory arena: a POSIX shm segment containing
// a process-shared mutex, an open-addressing object table and a free-list
// allocator. Every process maps the segment and operates on it directly —
// no server hop, no socket round-trip, create/get are O(1) under one lock.
// That fits the TPU host profile: few large tensor buffers produced by
// per-host input pipelines and consumed zero-copy by the JAX runtime.
//
// Semantics kept from the reference: immutable sealed objects, pin-by-
// refcount gets, LRU eviction of unpinned sealed objects when allocation
// needs space (eviction_policy.cc), create→seal lifecycle.
//
// Exposed as a flat C ABI for ctypes (no pybind11 in this image).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52415954505553ULL;  // "RAYTPUS"
constexpr uint32_t kKeySize = 16;

enum SlotState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
  // Deleted-while-pinned: invisible to lookups (get/contains/create see
  // through it), bytes freed on the LAST release. Plasma's deferred
  // deletion — a zero-copy reader must never have its mapping recycled
  // under it because the owner freed the object first.
  kDoomed = 4,
};

struct Slot {
  uint8_t key[kKeySize];
  uint64_t offset;  // into data region
  uint64_t size;
  uint32_t state;
  uint32_t refcount;
  uint64_t last_access;  // lru clock value
  // Monotonic creation stamp. Release is addressed by (key, gen), not key
  // alone: after doom + re-create of the same key (possibly at the same
  // offset), a stale reader's release must hit ITS generation, never
  // unpin the successor.
  uint64_t gen;
};

// Free block header lives inside the data region at the block's offset.
struct FreeBlock {
  uint64_t size;
  uint64_t next;  // offset of next free block, or 0 (data offset 0 is never
                  // a valid block start because block 0 is the initial span)
};

constexpr uint64_t kNil = ~0ULL;

struct Header {
  uint64_t magic;
  pthread_mutex_t mutex;
  uint64_t table_slots;
  uint64_t table_offset;  // from segment base
  uint64_t data_offset;
  uint64_t capacity;  // data region bytes
  uint64_t used_bytes;
  uint64_t lru_clock;
  uint64_t free_head;  // offset into data region, kNil = none
  uint64_t num_objects;
  // When set, a full arena FAILS creates instead of LRU-evicting sealed
  // objects. Eviction is cache semantics; a node's store holds the ONLY
  // copy of task results — silently discarding one leaves a phantom
  // location at the head and a driver polling it forever. Overflow is
  // handled by the caller (spill-to-disk). Shared: every attacher must
  // honor it.
  uint64_t no_evict;
};

struct Store {
  int fd;
  void* base;
  uint64_t map_size;
  Header* hdr;
  Slot* table;
  uint8_t* data;
  char name[256];
  bool owner;
};

uint64_t hash_key(const uint8_t* key) {
  // FNV-1a over 16 bytes.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kKeySize; i++) {
    h ^= key[i];
    h *= 1099511628211ULL;
  }
  return h;
}

Slot* find_slot(Store* s, const uint8_t* key, bool for_insert) {
  uint64_t mask = s->hdr->table_slots - 1;
  uint64_t idx = hash_key(key) & mask;
  Slot* first_tomb = nullptr;
  for (uint64_t probe = 0; probe <= mask; probe++, idx = (idx + 1) & mask) {
    Slot* slot = &s->table[idx];
    if (slot->state == kEmpty) {
      if (for_insert) return first_tomb ? first_tomb : slot;
      return nullptr;
    }
    if (slot->state == kTombstone) {
      if (first_tomb == nullptr) first_tomb = slot;
      continue;
    }
    if (slot->state == kDoomed) continue;  // invisible: freed on last release
    if (memcmp(slot->key, key, kKeySize) == 0) return slot;
  }
  return for_insert ? first_tomb : nullptr;
}

// Locate a specific generation of a key — doomed slots included. Only the
// release path needs this (a pin always names the generation it took).
Slot* find_gen(Store* s, const uint8_t* key, uint64_t gen) {
  uint64_t mask = s->hdr->table_slots - 1;
  uint64_t idx = hash_key(key) & mask;
  for (uint64_t probe = 0; probe <= mask; probe++, idx = (idx + 1) & mask) {
    Slot* slot = &s->table[idx];
    if (slot->state == kEmpty) return nullptr;
    if (slot->state == kTombstone) continue;
    if (slot->gen == gen && memcmp(slot->key, key, kKeySize) == 0) return slot;
  }
  return nullptr;
}

// --- allocator: address-ordered first-fit free list with coalescing --------

uint64_t alloc_block(Store* s, uint64_t size) {
  // Round to 64 bytes (cacheline); minimum block holds a FreeBlock header.
  size = (size + 63) & ~63ULL;
  if (size < sizeof(FreeBlock)) size = sizeof(FreeBlock);
  uint64_t prev = kNil;
  uint64_t cur = s->hdr->free_head;
  while (cur != kNil) {
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(s->data + cur);
    if (fb->size >= size) {
      uint64_t remaining = fb->size - size;
      uint64_t next = fb->next;
      if (remaining >= 64 + sizeof(FreeBlock)) {
        uint64_t split = cur + size;
        FreeBlock* nb = reinterpret_cast<FreeBlock*>(s->data + split);
        nb->size = remaining;
        nb->next = next;
        next = split;
      } else {
        size = fb->size;  // absorb the tail fragment
      }
      if (prev == kNil) {
        s->hdr->free_head = next;
      } else {
        reinterpret_cast<FreeBlock*>(s->data + prev)->next = next;
      }
      s->hdr->used_bytes += size;
      return cur;
    }
    prev = cur;
    cur = fb->next;
  }
  return kNil;
}

void free_block(Store* s, uint64_t offset, uint64_t size) {
  size = (size + 63) & ~63ULL;
  if (size < sizeof(FreeBlock)) size = sizeof(FreeBlock);
  s->hdr->used_bytes -= size;
  // Insert address-ordered; coalesce with neighbors.
  uint64_t prev = kNil;
  uint64_t cur = s->hdr->free_head;
  while (cur != kNil && cur < offset) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(s->data + cur)->next;
  }
  FreeBlock* nb = reinterpret_cast<FreeBlock*>(s->data + offset);
  nb->size = size;
  nb->next = cur;
  if (prev == kNil) {
    s->hdr->free_head = offset;
  } else {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(s->data + prev);
    if (prev + pb->size == offset) {  // coalesce with prev
      pb->size += size;
      pb->next = cur;
      nb = pb;
      offset = prev;
    } else {
      pb->next = offset;
    }
  }
  if (cur != kNil && offset + nb->size == cur) {  // coalesce with next
    FreeBlock* cb = reinterpret_cast<FreeBlock*>(s->data + cur);
    nb->size += cb->size;
    nb->next = cb->next;
  }
}

// Evict unpinned sealed objects, LRU-first, until `needed` bytes could fit.
// Reference: plasma EvictionPolicy::ChooseObjectsToEvict.
bool evict_for(Store* s, uint64_t needed) {
  if (s->hdr->no_evict) return false;
  needed = (needed + 63) & ~63ULL;
  while (true) {
    if (s->hdr->capacity - s->hdr->used_bytes >= needed) {
      // There may be enough *total* free bytes but fragmented; try alloc at
      // the call site — here we just bound total usage.
      return true;
    }
    Slot* victim = nullptr;
    for (uint64_t i = 0; i < s->hdr->table_slots; i++) {
      Slot* slot = &s->table[i];
      if (slot->state == kSealed && slot->refcount == 0) {
        if (victim == nullptr || slot->last_access < victim->last_access) {
          victim = slot;
        }
      }
    }
    if (victim == nullptr) return false;
    free_block(s, victim->offset, victim->size);
    victim->state = kTombstone;
    s->hdr->num_objects--;
  }
}

void lock(Store* s) { pthread_mutex_lock(&s->hdr->mutex); }
void unlock(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0) a store segment.
// Returns an opaque handle or nullptr. table_slots must be a power of two.
void* shm_store_open(const char* name, uint64_t capacity,
                     uint64_t table_slots, int create) {
  int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0 && create && errno == EEXIST) {
    shm_unlink(name);  // stale segment from a crashed run
    fd = shm_open(name, flags, 0600);
  }
  if (fd < 0) return nullptr;

  uint64_t table_bytes = table_slots * sizeof(Slot);
  uint64_t data_offset =
      (sizeof(Header) + table_bytes + 4095) & ~4095ULL;  // page align
  uint64_t map_size = data_offset + capacity;

  if (create) {
    if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
      close(fd);
      shm_unlink(name);
      return nullptr;
    }
  } else {
    // Attaching: the segment defines its own geometry.
    struct stat st;
    if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
      close(fd);
      return nullptr;
    }
    map_size = static_cast<uint64_t>(st.st_size);
  }
  void* base =
      mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    if (create) shm_unlink(name);
    return nullptr;
  }

  Store* s = new Store();
  s->fd = fd;
  s->base = base;
  s->map_size = map_size;
  s->hdr = reinterpret_cast<Header*>(base);
  s->owner = create != 0;
  strncpy(s->name, name, sizeof(s->name) - 1);

  if (create) {
    Header* h = s->hdr;
    memset(h, 0, sizeof(Header));
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
    h->table_slots = table_slots;
    h->table_offset = sizeof(Header);
    h->data_offset = data_offset;
    h->capacity = capacity;
    h->used_bytes = 0;
    h->lru_clock = 1;
    h->num_objects = 0;
    // Loss-proof by default: callers opt INTO cache semantics
    // (shm_store_set_no_evict(h, 0)) when every object is re-fetchable.
    h->no_evict = 1;
    memset(reinterpret_cast<uint8_t*>(base) + h->table_offset, 0, table_bytes);
    // One giant free block spanning the data region.
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(
        reinterpret_cast<uint8_t*>(base) + data_offset);
    fb->size = capacity;
    fb->next = kNil;
    h->free_head = 0;
    h->magic = kMagic;  // last: signals fully initialized
  } else if (s->hdr->magic != kMagic) {
    munmap(base, map_size);
    close(fd);
    delete s;
    return nullptr;
  }
  s->table = reinterpret_cast<Slot*>(reinterpret_cast<uint8_t*>(base) +
                                     s->hdr->table_offset);
  s->data = reinterpret_cast<uint8_t*>(base) + s->hdr->data_offset;
  return s;
}

void shm_store_close(void* handle, int unlink_segment) {
  Store* s = static_cast<Store*>(handle);
  if (s == nullptr) return;
  munmap(s->base, s->map_size);
  close(s->fd);
  if (unlink_segment) shm_unlink(s->name);
  delete s;
}

// Allocate an object buffer for zero-copy writes. Returns the offset of the
// buffer relative to the mapping base (for Python-side memoryview slicing),
// or -1 on failure (full / exists).
int64_t shm_store_create(void* handle, const uint8_t* key, uint64_t size) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* existing = find_slot(s, key, false);
  if (existing != nullptr) {
    unlock(s);
    return -1;
  }
  uint64_t off = alloc_block(s, size);
  if (off == kNil) {
    if (!evict_for(s, size)) {
      unlock(s);
      return -1;
    }
    off = alloc_block(s, size);
    if (off == kNil) {  // fragmented beyond repair for this size
      unlock(s);
      return -1;
    }
  }
  Slot* slot = find_slot(s, key, true);
  if (slot == nullptr) {  // table full
    free_block(s, off, size);
    unlock(s);
    return -1;
  }
  memcpy(slot->key, key, kKeySize);
  slot->offset = off;
  slot->size = size;
  slot->state = kCreated;
  slot->refcount = 1;  // creator holds a pin until seal/abort
  slot->last_access = s->hdr->lru_clock++;
  slot->gen = s->hdr->lru_clock++;
  s->hdr->num_objects++;
  unlock(s);
  return static_cast<int64_t>(s->hdr->data_offset + off);
}

// Discard a created-but-unsealed object (creator gave up: failed receive,
// aborted transfer). The region returns to the free list; the key becomes
// creatable again. No effect on sealed objects. Partial-write audit: a
// kCreated region is never visible to get/contains/evict, so a half-written
// buffer can only ever be reclaimed here or published by seal — there is no
// path that reads it.
int shm_store_abort(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, key, false);
  if (slot == nullptr || slot->state != kCreated) {
    unlock(s);
    return -1;
  }
  free_block(s, slot->offset, slot->size);
  slot->state = kTombstone;
  s->hdr->num_objects--;
  unlock(s);
  return 0;
}

int shm_store_seal(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, key, false);
  if (slot == nullptr || slot->state != kCreated) {
    unlock(s);
    return -1;
  }
  slot->state = kSealed;
  slot->refcount = 0;
  unlock(s);
  return 0;
}

// Pin + locate a sealed object. Returns 0 and fills offset/size, else -1.
int shm_store_get(void* handle, const uint8_t* key, int64_t* offset,
                  uint64_t* size) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, key, false);
  if (slot == nullptr || slot->state != kSealed) {
    unlock(s);
    return -1;
  }
  slot->refcount++;
  slot->last_access = s->hdr->lru_clock++;
  *offset = static_cast<int64_t>(s->hdr->data_offset + slot->offset);
  *size = slot->size;
  unlock(s);
  return 0;
}

// Pin + locate, returning the slot generation as well — the release token
// for zero-copy readers (see Slot::gen).
int shm_store_get2(void* handle, const uint8_t* key, int64_t* offset,
                   uint64_t* size, uint64_t* gen) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, key, false);
  if (slot == nullptr || slot->state != kSealed) {
    unlock(s);
    return -1;
  }
  slot->refcount++;
  slot->last_access = s->hdr->lru_clock++;
  *offset = static_cast<int64_t>(s->hdr->data_offset + slot->offset);
  *size = slot->size;
  *gen = slot->gen;
  unlock(s);
  return 0;
}

int shm_store_release(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, key, false);
  if (slot == nullptr || slot->refcount == 0) {
    unlock(s);
    return -1;
  }
  slot->refcount--;
  unlock(s);
  return 0;
}

// Generation-addressed unpin. Drops the bytes of a doomed object on its
// last release; a stale release (generation long gone) is a no-op, never a
// mispin of the key's successor.
int shm_store_release_gen(void* handle, const uint8_t* key, uint64_t gen) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_gen(s, key, gen);
  if (slot == nullptr || slot->refcount == 0) {
    unlock(s);
    return -1;
  }
  slot->refcount--;
  if (slot->refcount == 0 && slot->state == kDoomed) {
    free_block(s, slot->offset, slot->size);
    slot->state = kTombstone;  // num_objects already dropped at doom time
  }
  unlock(s);
  return 0;
}

int shm_store_contains(void* handle, const uint8_t* key) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, key, false);
  int found = (slot != nullptr && slot->state == kSealed) ? 1 : 0;
  unlock(s);
  return found;
}

// Delete an object. A pinned object (live zero-copy readers) is DOOMED
// instead of freed: it vanishes from lookups immediately, but its bytes
// survive until the last shm_store_release_gen — the reader's view stays
// valid across the producer's delete (churn safety). `force` frees
// immediately regardless of pins (shutdown path).
int shm_store_delete(void* handle, const uint8_t* key, int force) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  Slot* slot = find_slot(s, key, false);
  if (slot == nullptr || slot->state == kEmpty || slot->state == kTombstone) {
    unlock(s);
    return -1;
  }
  if (slot->refcount > 0 && !force) {
    if (slot->state == kCreated) {
      unlock(s);
      return -2;  // mid-create: the creator's pin; abort() is the tool
    }
    slot->state = kDoomed;
    s->hdr->num_objects--;
    unlock(s);
    return 0;  // deferred: bytes freed on last release
  }
  free_block(s, slot->offset, slot->size);
  slot->state = kTombstone;
  s->hdr->num_objects--;
  unlock(s);
  return 0;
}

uint64_t shm_store_used_bytes(void* handle) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  uint64_t v = s->hdr->used_bytes;
  unlock(s);
  return v;
}

uint64_t shm_store_capacity(void* handle) {
  return static_cast<Store*>(handle)->hdr->capacity;
}

uint64_t shm_store_num_objects(void* handle) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  uint64_t v = s->hdr->num_objects;
  unlock(s);
  return v;
}

int shm_store_fd(void* handle) { return static_cast<Store*>(handle)->fd; }

// Toggle loss-proof mode (see Header::no_evict). Safe from any attacher.
void shm_store_set_no_evict(void* handle, int enable) {
  Store* s = static_cast<Store*>(handle);
  lock(s);
  s->hdr->no_evict = enable ? 1 : 0;
  unlock(s);
}

uint64_t shm_store_map_size(void* handle) {
  return static_cast<Store*>(handle)->map_size;
}

}  // extern "C"
