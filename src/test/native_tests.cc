// Native-component test harness, built under ASAN/UBSAN and TSAN
// (reference test strategy: SURVEY.md §4 item 6 — the reference runs its
// gtest suites under sanitizer CI builds, ci/ray_ci/tester.py). Plain
// asserts, no gtest dependency in this image.
//
// Build + run: `make -C src sanitize` (asan+ubsan) / `make -C src tsan`.

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// C APIs of the two native components.
extern "C" {
void* shm_store_open(const char* name, uint64_t capacity,
                     uint64_t table_slots, int create);
void shm_store_set_no_evict(void* handle, int enable);
void shm_store_close(void* handle, int unlink_segment);
int64_t shm_store_create(void* handle, const uint8_t* key, uint64_t size);
int shm_store_seal(void* handle, const uint8_t* key);
int shm_store_get(void* handle, const uint8_t* key, int64_t* offset,
                  uint64_t* size);
int shm_store_release(void* handle, const uint8_t* key);
int shm_store_contains(void* handle, const uint8_t* key);
int shm_store_delete(void* handle, const uint8_t* key, int force);
uint64_t shm_store_used_bytes(void* handle);
uint64_t shm_store_num_objects(void* handle);
uint64_t shm_store_map_size(void* handle);

int64_t topo_create(const int* shape, int ndim);
void topo_destroy(int64_t id);
int64_t topo_num_free(int64_t id);
int64_t topo_alloc_subcube(int64_t id, int64_t chips, int* out_coords);
int64_t topo_alloc_any(int64_t id, int64_t chips, int* out_coords);
void topo_release(int64_t id, const int* coords, int64_t n);
int64_t score_nodes(const double* avail, const double* total,
                    int64_t n_nodes, int64_t n_res, const double* request,
                    double spread_threshold);
}

namespace {

constexpr int kKeySize = 16;

void make_key(uint8_t* key, int i) {
  std::memset(key, 0, kKeySize);
  std::snprintf(reinterpret_cast<char*>(key), kKeySize, "k%06d", i);
}

void* open_store(const char* name) {
  void* s = shm_store_open(name, 1 << 20, 256, 1);
  assert(s != nullptr);
  return s;
}

void test_store_lifecycle() {
  void* s = open_store("/raytpu_test_lc");
  uint8_t key[kKeySize];
  make_key(key, 1);
  int64_t off = shm_store_create(s, key, 4096);
  assert(off > 0);
  assert(shm_store_contains(s, key) == 0);  // not sealed yet
  assert(shm_store_seal(s, key) == 0);
  assert(shm_store_contains(s, key) == 1);
  int64_t got_off = 0;
  uint64_t got_size = 0;
  assert(shm_store_get(s, key, &got_off, &got_size) == 0);
  assert(got_off == off && got_size == 4096);
  assert(shm_store_delete(s, key, 0) == -2);  // pinned
  assert(shm_store_release(s, key) == 0);
  assert(shm_store_delete(s, key, 0) == 0);
  assert(shm_store_contains(s, key) == 0);
  shm_store_close(s, 1);
  std::printf("store lifecycle ok\n");
}

void test_store_eviction_and_reuse() {
  void* s = open_store("/raytpu_test_ev");
  shm_store_set_no_evict(s, 0);  // cache semantics are opt-in now
  // Fill past capacity with unpinned sealed objects; LRU eviction must
  // keep creates succeeding.
  for (int i = 0; i < 64; i++) {
    uint8_t key[kKeySize];
    make_key(key, i);
    int64_t off = shm_store_create(s, key, 64 * 1024);
    assert(off > 0);
    assert(shm_store_seal(s, key) == 0);
  }
  assert(shm_store_used_bytes(s) <= (1u << 20));
  assert(shm_store_num_objects(s) <= 16);  // 1MiB / 64KiB
  shm_store_close(s, 1);
  std::printf("store eviction ok\n");
}

void test_store_no_evict_default() {
  // Creation default is loss-proof: a full arena fails creates and
  // nothing sealed is discarded.
  void* s = open_store("/raytpu_test_ne");
  int created = 0;
  for (int i = 0; i < 64; i++) {
    uint8_t key[kKeySize];
    make_key(key, i);
    int64_t off = shm_store_create(s, key, 64 * 1024);
    if (off < 0) break;
    assert(shm_store_seal(s, key) == 0);
    created++;
  }
  assert(created >= 8 && created < 64);  // filled, then failed
  for (int i = 0; i < created; i++) {
    uint8_t key[kKeySize];
    make_key(key, i);
    assert(shm_store_contains(s, key) == 1);  // nothing discarded
  }
  shm_store_close(s, 1);
  std::printf("store no-evict default ok\n");
}

void test_store_concurrent() {
  // Two threads hammer disjoint key ranges through one mapping — the
  // TSAN target: the shared header mutex must serialize all metadata.
  void* s = open_store("/raytpu_test_mt");
  auto worker = [&](int base) {
    for (int i = 0; i < 200; i++) {
      uint8_t key[kKeySize];
      make_key(key, base + i);
      if (shm_store_create(s, key, 1024) < 0) continue;
      shm_store_seal(s, key);
      int64_t off;
      uint64_t size;
      if (shm_store_get(s, key, &off, &size) == 0) {
        shm_store_release(s, key);
      }
      shm_store_delete(s, key, 0);
    }
  };
  std::thread a(worker, 0), b(worker, 100000);
  a.join();
  b.join();
  shm_store_close(s, 1);
  std::printf("store concurrent ok\n");
}

void test_topo_subcube() {
  int shape[3] = {2, 2, 2};
  int64_t id = topo_create(shape, 3);
  assert(id >= 0);
  assert(topo_num_free(id) == 8);
  int coords[8 * 3];
  assert(topo_alloc_subcube(id, 4, coords) == 4);
  assert(topo_num_free(id) == 4);
  assert(topo_alloc_subcube(id, 8, coords) == 0);  // doesn't fit now
  int rest[4 * 3];
  assert(topo_alloc_any(id, 4, rest) == 4);
  assert(topo_num_free(id) == 0);
  topo_release(id, coords, 4);
  topo_release(id, rest, 4);
  assert(topo_num_free(id) == 8);
  topo_destroy(id);
  std::printf("topo subcube ok\n");
}

void test_topo_concurrent() {
  int shape[2] = {8, 8};
  int64_t id = topo_create(shape, 2);
  auto worker = [&]() {
    int coords[4 * 2];
    for (int i = 0; i < 500; i++) {
      int64_t got = topo_alloc_any(id, 4, coords);
      if (got > 0) topo_release(id, coords, got);
    }
  };
  std::thread a(worker), b(worker), c(worker);
  a.join();
  b.join();
  c.join();
  assert(topo_num_free(id) == 64);
  topo_destroy(id);
  std::printf("topo concurrent ok\n");
}

void test_score_nodes() {
  // Two nodes, one resource. Pack phase: pick the MORE utilized feasible
  // node while below the spread threshold.
  double avail[] = {8.0, 2.0};
  double total[] = {8.0, 8.0};
  double req[] = {1.0};
  // node1 util 0.75 >= threshold 0.5 -> spread to least utilized (node0)
  assert(score_nodes(avail, total, 2, 1, req, 0.5) == 0);
  double avail2[] = {7.0, 8.0};
  // utils 0.125/0.0, both below threshold -> pack onto node0
  assert(score_nodes(avail2, total, 2, 1, req, 0.5) == 0);
  double req_big[] = {16.0};
  assert(score_nodes(avail, total, 2, 1, req_big, 0.5) == -1);
  std::printf("score_nodes ok\n");
}

}  // namespace

int main() {
  test_store_lifecycle();
  test_store_eviction_and_reuse();
  test_store_no_evict_default();
  test_store_concurrent();
  test_topo_subcube();
  test_topo_concurrent();
  test_score_nodes();
  std::printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
