"""raytpu — a TPU-native distributed AI runtime.

A brand-new framework with the capabilities of Ray (reference surveyed in
``SURVEY.md``), designed TPU-first: a host-process fabric providing tasks,
actors, owned objects and placement groups (reference analogue:
``python/ray/_private/worker.py``, ``src/ray/core_worker/``), where the
schedulable resource is the TPU chip/slice with ICI topology as a first-class
scheduling dimension, and where every numeric component is a compiled XLA
program over a ``jax.sharding.Mesh`` — collectives ride ICI inside the
program rather than NCCL outside it.

Public API mirrors the reference's core surface (``ray.init/remote/get/put/
wait``; reference: ``python/ray/_private/worker.py:1217,2554,2686``) so a
Ray user can switch with minimal relearning.
"""

from raytpu._version import __version__
from raytpu.core.errors import (
    RayTpuError,
    TaskError,
    ActorError,
    ActorDiedError,
    ObjectLostError,
    WorkerCrashedError,
    GetTimeoutError,
    RuntimeEnvError,
)
from raytpu.core.ids import ObjectID, TaskID, ActorID, NodeID, JobID, PlacementGroupID
from raytpu.runtime.api import (
    init,
    shutdown,
    is_initialized,
    remote,
    get,
    put,
    wait,
    cancel,
    kill,
    get_actor,
    method,
    get_runtime_context,
    available_resources,
    cluster_resources,
    nodes,
    timeline,
)
from raytpu.runtime.generator import ObjectRefGenerator
from raytpu.runtime.object_ref import ObjectRef
from raytpu.runtime.placement_group import (
    placement_group,
    PlacementGroup,
    remove_placement_group,
    get_current_placement_group,
)

# Subpackages (imported lazily by users): raytpu.data, raytpu.train,
# raytpu.tune, raytpu.serve, raytpu.rllib, raytpu.parallel, raytpu.ops,
# raytpu.collective, raytpu.util

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "cancel",
    "kill",
    "get_actor",
    "method",
    "get_runtime_context",
    "available_resources",
    "cluster_resources",
    "nodes",
    "timeline",
    "ObjectRef",
    "ObjectRefGenerator",
    "placement_group",
    "PlacementGroup",
    "remove_placement_group",
    "get_current_placement_group",
    "RayTpuError",
    "TaskError",
    "ActorError",
    "ActorDiedError",
    "ObjectLostError",
    "WorkerCrashedError",
    "GetTimeoutError",
    "RuntimeEnvError",
    "ObjectID",
    "TaskID",
    "ActorID",
    "NodeID",
    "JobID",
    "PlacementGroupID",
]
