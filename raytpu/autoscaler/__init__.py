"""raytpu.autoscaler — slice-atomic node-group autoscaling.

Reference analogue: ``python/ray/autoscaler/`` (v1 StandardAutoscaler +
v2 reconciler; see module docstrings for the mapping).
"""

from raytpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    AutoscalerMonitor,
    ResourceDemand,
    StandardAutoscaler,
)
from raytpu.autoscaler.node_provider import (
    FakeSliceProvider,
    GceTpuSliceProvider,
    NodeGroup,
    NodeGroupSpec,
    NodeProvider,
)

__all__ = [
    "AutoscalerConfig", "AutoscalerMonitor", "FakeSliceProvider",
    "GceTpuSliceProvider",
    "NodeGroup", "NodeGroupSpec", "NodeProvider", "ResourceDemand",
    "StandardAutoscaler",
]
