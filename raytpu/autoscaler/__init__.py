"""raytpu.autoscaler — slice-atomic node-group autoscaling.

Reference analogue: ``python/ray/autoscaler/`` (v1 StandardAutoscaler +
v2 reconciler; see module docstrings for the mapping).
"""

from raytpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    AutoscalerMonitor,
    ResourceDemand,
    StandardAutoscaler,
)
from raytpu.autoscaler.bridge import (
    GROUP_LABEL,
    DrainingProvider,
    HeadDemandFeed,
    connect_autoscaler,
)
from raytpu.autoscaler.launcher import (
    cluster_down,
    cluster_up,
    load_cluster_spec,
    load_cluster_state,
)
from raytpu.autoscaler.node_provider import (
    FakeSliceProvider,
    GceTpuSliceProvider,
    K8sSliceProvider,
    NodeGroup,
    NodeGroupSpec,
    NodeProvider,
)
from raytpu.autoscaler.sdk import request_resources

__all__ = [
    "AutoscalerConfig", "AutoscalerMonitor", "DrainingProvider",
    "FakeSliceProvider", "GROUP_LABEL", "GceTpuSliceProvider",
    "HeadDemandFeed", "K8sSliceProvider",
    "NodeGroup", "NodeGroupSpec", "NodeProvider", "ResourceDemand",
    "StandardAutoscaler", "cluster_down", "cluster_up",
    "connect_autoscaler", "load_cluster_spec", "load_cluster_state",
    "request_resources",
]
