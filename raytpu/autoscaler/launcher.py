"""YAML cluster launcher: ``raytpu up / down``.

Reference analogue: ``python/ray/scripts/scripts.py:1278`` (``ray up``)
+ ``autoscaler/_private/commands.py`` — a YAML cluster spec is turned
into provider calls that bring up a head and the minimum worker groups,
and ``down`` tears the same cluster back down. The reference bootstraps
over SSH; ours drives the slice NodeProviders (GCE/K8s/fake) through
the same declarative :class:`InstanceManager` the autoscaler uses, so
``up`` is literally "reconcile until the targets are RUNNING".

Spec shape (YAML)::

    cluster_name: demo
    provider:
      type: fake | gce | k8s        # + provider-specific keys:
      # gce: project, zone, runtime_version
      # k8s: namespace, image
    idle_timeout_s: 60              # autoscaler knob (optional)
    head:
      group: cpu-head               # which node_groups entry is the head
    node_groups:
      cpu-head:
        resources_per_host: {CPU: 8}
      v5e-8:
        hosts: 1
        resources_per_host: {TPU: 8, CPU: 8}
        min_workers: 2
        max_workers: 4

Cluster state (provider config + name) persists under
``~/.raytpu/clusters/<name>.json`` so ``raytpu down <name>`` works
without the original YAML.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from raytpu.autoscaler.instance_manager import RUNNING, InstanceManager
from raytpu.autoscaler.node_provider import NodeGroupSpec, NodeProvider

_STATE_DIR = os.path.join(os.path.expanduser("~/.raytpu"), "clusters")


@dataclass
class ClusterSpec:
    cluster_name: str
    provider: Dict[str, object]
    node_groups: Dict[str, NodeGroupSpec]
    head_group: Optional[str] = None
    min_targets: Dict[str, int] = field(default_factory=dict)
    idle_timeout_s: float = 60.0


def load_cluster_spec(path_or_dict) -> ClusterSpec:
    if isinstance(path_or_dict, dict):
        raw = path_or_dict
    else:
        import yaml

        with open(path_or_dict) as f:
            raw = yaml.safe_load(f)
    if not isinstance(raw, dict) or not raw.get("cluster_name"):
        raise ValueError("cluster spec needs a 'cluster_name'")
    if not isinstance(raw.get("provider"), dict) \
            or not raw["provider"].get("type"):
        raise ValueError("cluster spec needs provider.type")
    groups_raw = raw.get("node_groups")
    if not isinstance(groups_raw, dict) or not groups_raw:
        raise ValueError("cluster spec needs at least one node_groups "
                         "entry")
    specs: Dict[str, NodeGroupSpec] = {}
    targets: Dict[str, int] = {}
    for name, g in groups_raw.items():
        g = g or {}
        unknown = set(g) - {"hosts", "resources_per_host", "topology",
                            "min_workers", "max_workers"}
        if unknown:
            raise ValueError(f"node_groups[{name!r}]: unknown keys "
                             f"{sorted(unknown)}")
        specs[name] = NodeGroupSpec(
            name,
            hosts=int(g.get("hosts", 1)),
            resources_per_host={k: float(v) for k, v in
                                (g.get("resources_per_host") or {}).items()},
            topology=tuple(g["topology"]) if g.get("topology") else None,
            min_groups=int(g.get("min_workers", 0)),
            max_groups=int(g.get("max_workers",
                                 max(1, int(g.get("min_workers", 0))))),
        )
        targets[name] = specs[name].min_groups
    head_group = (raw.get("head") or {}).get("group")
    if head_group is not None:
        if head_group not in specs:
            raise ValueError(f"head.group {head_group!r} is not a "
                             f"node_groups entry")
        targets[head_group] = max(1, targets.get(head_group, 0))
    return ClusterSpec(
        cluster_name=str(raw["cluster_name"]),
        provider=dict(raw["provider"]),
        node_groups=specs,
        head_group=head_group,
        min_targets=targets,
        idle_timeout_s=float(raw.get("idle_timeout_s", 60.0)),
    )


def make_provider(provider_cfg: Dict[str, object],
                  runner=None) -> NodeProvider:
    """Provider factory. ``runner`` injects the fake CLI runner in tests
    (same pattern the provider unit tests use)."""
    from raytpu.autoscaler.node_provider import (
        FakeSliceProvider,
        GceTpuSliceProvider,
        K8sSliceProvider,
    )

    cfg = dict(provider_cfg)
    ptype = str(cfg.pop("type"))
    if ptype == "fake":
        return FakeSliceProvider(
            provision_ticks=int(cfg.pop("provision_ticks", 1)))
    if ptype == "gce":
        kwargs = {k: cfg[k] for k in
                  ("project", "zone", "runtime_version", "name_prefix")
                  if k in cfg}
        return GceTpuSliceProvider(runner=runner, **kwargs)
    if ptype == "k8s":
        kwargs = {k: cfg[k] for k in
                  ("namespace", "image", "name_prefix", "pod_template")
                  if k in cfg}
        return K8sSliceProvider(runner=runner, **kwargs)
    raise ValueError(f"unknown provider type {ptype!r} "
                     f"(supported: fake, gce, k8s)")


def _state_path(name: str) -> str:
    return os.path.join(_STATE_DIR, f"{name}.json")


def _save_state(spec: ClusterSpec) -> None:
    os.makedirs(_STATE_DIR, exist_ok=True)
    state = {
        "cluster_name": spec.cluster_name,
        "provider": spec.provider,
        "idle_timeout_s": spec.idle_timeout_s,
        "node_groups": {
            n: {"hosts": s.hosts,
                "resources_per_host": s.resources_per_host,
                **({"topology": list(s.topology)} if s.topology else {}),
                "min_workers": s.min_groups,
                "max_workers": s.max_groups}
            for n, s in spec.node_groups.items()},
        "head": {"group": spec.head_group} if spec.head_group else {},
    }
    tmp = _state_path(spec.cluster_name) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=2)
    os.replace(tmp, _state_path(spec.cluster_name))


def load_cluster_state(name: str) -> ClusterSpec:
    path = _state_path(name)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no recorded cluster {name!r} under {_STATE_DIR}; pass the "
            f"original YAML instead")
    with open(path) as f:
        return load_cluster_spec(json.load(f))


def cluster_up(spec: ClusterSpec, *, provider: Optional[NodeProvider]
               = None, runner=None, timeout_s: float = 600.0,
               poll_interval_s: float = 1.0,
               on_progress=None) -> Dict[str, object]:
    """Bring the cluster to its minimum footprint: head group + every
    group's ``min_workers``, reconciled until RUNNING. Idempotent: the
    reconciler adopts groups that already exist (re-running ``up`` on a
    live cluster converges without relaunching)."""
    provider = provider or make_provider(spec.provider, runner=runner)
    im = InstanceManager(provider, spec.node_groups)
    im.set_targets(spec.min_targets)
    want_total = sum(spec.min_targets.values())
    deadline = time.monotonic() + timeout_s
    while True:
        im.reconcile(idle_timeout_s=spec.idle_timeout_s)
        running = im.instances(states={RUNNING})
        if len(running) >= want_total:
            break
        if time.monotonic() > deadline:
            by_state: Dict[str, int] = {}
            for inst in im.instances():
                by_state[inst.state] = by_state.get(inst.state, 0) + 1
            raise TimeoutError(
                f"cluster {spec.cluster_name!r} did not reach "
                f"{want_total} running groups in {timeout_s}s "
                f"(instances: {by_state})")
        if on_progress is not None:
            on_progress(len(running), want_total)
        time.sleep(poll_interval_s)
    _save_state(spec)
    groups = [{
        "group_id": inst.group_id,
        "type": inst.group_type,
        "role": ("head" if spec.head_group == inst.group_type
                 else "worker"),
        "hosts": list(inst.group.host_ids) if inst.group else [],
    } for inst in im.instances(states={RUNNING})]
    return {"cluster_name": spec.cluster_name, "groups": groups,
            "instance_manager": im, "provider": provider}


def cluster_down(spec: ClusterSpec, *, provider: Optional[NodeProvider]
                 = None, runner=None) -> List[str]:
    """Terminate every non-terminated group of the cluster's provider
    scope and drop the recorded state. Returns terminated group ids."""
    provider = provider or make_provider(spec.provider, runner=runner)
    provider.poll()
    terminated: List[str] = []
    for g in list(provider.non_terminated_groups()):
        provider.terminate_node_group(g.group_id)
        terminated.append(g.group_id)
    try:
        os.remove(_state_path(spec.cluster_name))
    except OSError:
        pass
    return terminated


__all__ = ["ClusterSpec", "load_cluster_spec", "load_cluster_state",
           "make_provider", "cluster_up", "cluster_down"]
