"""Head ↔ autoscaler bridge: pressure-driven scaling off the head's
``resource_demands`` feed.

Reference analogue: ``autoscaler/_private/monitor.py`` — the monitor
process polls GCS for cluster resource state
(``GcsAutoscalerStateManager::GetClusterResourceState``) and hands the
aggregated demand to the scaler. Here the head exports one RPC
(``resource_demands``) carrying three things at once:

* aggregated queued-infeasible demand — unschedulable task bundle
  shapes, pending (infeasible) placement-group bundles, and explicit
  ``request_resources`` hints;
* a per-node busy/idle census (labels included, so nodes launched by a
  provider group can be mapped back to it via the ``group_id`` label);
* the count of head-queued task specs.

:class:`HeadDemandFeed` turns that into the two callables
:class:`~raytpu.autoscaler.autoscaler.AutoscalerMonitor` wants
(``demand_fn`` / ``busy_fn``), and :class:`DrainingProvider` closes the
scale-down loop: before a surplus-idle group is terminated at the
cloud, every cluster node it hosts is drained through the head
(``drain_node(force=False)``) — and if the head refuses because the
node still hosts a live actor, the termination is aborted rather than
silently burning an actor restart. The busy census should prevent that
case from ever being selected; the drain refusal covers the race where
an actor lands between the census read and the terminate call.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set

from raytpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    AutoscalerMonitor,
    ResourceDemand,
    StandardAutoscaler,
)
from raytpu.autoscaler.node_provider import NodeProvider
from raytpu.cluster.protocol import ConnectionLost, RpcClient
from raytpu.util import errors

# Node label that maps a cluster node back to the provider group that
# launched it. Providers (or whatever boots the node process on a fresh
# slice) set it; the bridge's busy census and drain path key on it.
GROUP_LABEL = "group_id"


class HeadDemandFeed:
    """One ``resource_demands`` call per tick, fanned out to the three
    consumers (demand_fn, busy_fn, drain path) from a short-lived cache
    so the monitor's ``demand_fn()``/``busy_fn()`` pair costs one RPC,
    not two. Survives a head bounce: a lost connection is re-dialed
    once per call; while the head is down the feed reports no demand
    (scale decisions pause rather than act on stale state)."""

    def __init__(self, head_address: str,
                 cache_ttl_s: float = 0.25):
        self.head_address = head_address
        self._cache_ttl_s = cache_ttl_s
        self._lock = threading.Lock()
        self._client: Optional[RpcClient] = None
        self._snapshot: Optional[dict] = None
        self._snapshot_ts = 0.0

    # -- plumbing ----------------------------------------------------------

    def _call(self, method: str, *args):
        with self._lock:
            if self._client is None:
                self._client = RpcClient(self.head_address)
            client = self._client
        try:
            return client.call(method, *args)
        except ConnectionLost:
            # Head bounce: drop the dead client, re-dial once. A second
            # failure propagates — the monitor loop logs and retries
            # next tick.
            with self._lock:
                if self._client is client:
                    self._client = None
            try:
                client.close()
            except Exception as e:
                errors.swallow("autoscaler.feed_close", e)
            with self._lock:
                if self._client is None:
                    self._client = RpcClient(self.head_address)
                retry = self._client
            return retry.call(method, *args)

    def _state(self) -> dict:
        now = time.monotonic()
        with self._lock:
            snap, ts = self._snapshot, self._snapshot_ts
        if snap is not None and now - ts < self._cache_ttl_s:
            return snap
        fresh = self._call("resource_demands")
        with self._lock:
            self._snapshot, self._snapshot_ts = fresh, time.monotonic()
        return fresh

    # -- the monitor-facing surface ----------------------------------------

    def demands(self) -> List[ResourceDemand]:
        state = self._state()
        return [ResourceDemand(dict(d["bundle"]), int(d["count"]))
                for d in state.get("demands", [])]

    def busy_group_ids(self) -> Set[str]:
        """Provider groups hosting at least one busy node. Busy =
        running a live actor or holding allocated task resources (the
        head computes it; see ``_resource_demands``). Nodes labelled
        ``role=standby`` (hosting a hot-standby head follower) are
        always busy: scaling the follower away would silently forfeit
        zero-restart failover, so the group survives the idle census."""
        busy: Set[str] = set()
        for n in self._state().get("nodes", []):
            labels = n.get("labels") or {}
            gid = labels.get(GROUP_LABEL)
            standby = labels.get("role") == "standby"
            if gid and n.get("alive") and (n.get("busy") or standby):
                busy.add(gid)
        return busy

    def nodes_in_group(self, group_id: str) -> List[dict]:
        return [n for n in self._state().get("nodes", [])
                if n.get("alive")
                and (n.get("labels") or {}).get(GROUP_LABEL) == group_id]

    def drain_node(self, node_id: str, force: bool = False) -> dict:
        return self._call("drain_node", node_id, force)

    def close(self) -> None:
        with self._lock:
            client, self._client = self._client, None
        if client is not None:
            try:
                client.close()
            except Exception as e:
                errors.swallow("autoscaler.feed_close", e)


class DrainingProvider(NodeProvider):
    """Terminate-through-drain proxy. Every call except
    ``terminate_node_group`` delegates verbatim; termination first
    drains the group's cluster nodes at the head (``force=False``) so
    the head stops scheduling onto them and reroutes their state, and
    ABORTS (raises) if any node refuses the drain because it hosts a
    live actor. The instance manager records the raised reason on the
    instance's audit trail and the group survives to the next
    reconcile tick — where the busy census will keep it alive."""

    def __init__(self, inner: NodeProvider, feed: HeadDemandFeed):
        self.inner = inner
        self.feed = feed

    def create_node_group(self, spec):
        return self.inner.create_node_group(spec)

    def non_terminated_groups(self):
        return self.inner.non_terminated_groups()

    def poll(self) -> None:
        self.inner.poll()

    def terminate_node_group(self, group_id: str) -> None:
        for n in self.feed.nodes_in_group(group_id):
            verdict = self.feed.drain_node(n["node_id"], False)
            if not verdict.get("drained"):
                raise RuntimeError(
                    f"drain refused for node {n['node_id'][:12]} in "
                    f"group {group_id}: {verdict.get('actors', 0)} live "
                    f"actor(s) — aborting terminate")
        self.inner.terminate_node_group(group_id)


def connect_autoscaler(head_address: str,
                       config: AutoscalerConfig,
                       provider: NodeProvider,
                       period_s: float = 1.0,
                       on_update: Optional[
                           Callable[[Dict[str, int]], None]] = None,
                       ) -> AutoscalerMonitor:
    """Wire a head to an autoscaler: returns a started-when-you-say-so
    :class:`AutoscalerMonitor` whose demand comes from the head's
    ``resource_demands`` RPC and whose provider is wrapped in
    :class:`DrainingProvider` (drain-before-terminate). The feed is
    attached as ``monitor.feed`` and the draining provider as
    ``monitor.autoscaler.provider``; call ``monitor.start()`` to begin
    ticking and ``monitor.stop(); monitor.feed.close()`` to tear down.

    ``on_update`` (optional) observes each tick's launch counts —
    tests and dashboards hook it; errors inside it are swallowed so an
    observer can never stall scaling."""
    feed = HeadDemandFeed(head_address)
    draining = DrainingProvider(provider, feed)
    autoscaler = StandardAutoscaler(config, draining)
    if on_update is not None:
        inner_update = autoscaler.update

        def update(demands, busy_group_ids=None):
            launched = inner_update(demands, busy_group_ids)
            try:
                on_update(launched)
            except Exception as e:
                errors.swallow("autoscaler.on_update", e)
            return launched

        autoscaler.update = update  # type: ignore[method-assign]
    monitor = AutoscalerMonitor(autoscaler, demand_fn=feed.demands,
                                busy_fn=feed.busy_group_ids,
                                period_s=period_s)
    monitor.feed = feed  # teardown handle for callers
    return monitor


__all__ = ["DrainingProvider", "GROUP_LABEL", "HeadDemandFeed",
           "connect_autoscaler"]
