"""Declarative instance manager — the autoscaler's v2 reconciler core.

Reference analogue: ``python/ray/autoscaler/v2/instance_manager/
instance_manager.py:29`` — scaling is expressed as *desired state* (how
many instances of each type should exist) and a reconciler drives the
cloud toward it through an explicit per-instance state machine with an
audit trail, instead of imperative launch/terminate calls scattered
through the scaler. Slice-shaped here: the "instance" is a whole node
group (one TPU slice), matching the provider layer.

State machine (reference: v2 ``Instance.status`` values)::

    QUEUED -> REQUESTED -> ALLOCATED -> RUNNING -> TERMINATING -> TERMINATED
                 |             |           |
                 v             v           v
         ALLOCATION_FAILED   FAILED     FAILED   (drift: cloud lost it)

Reconcile-on-drift: a RUNNING instance whose cloud group vanishes or
fails flips to FAILED and the next tick launches a replacement (targets
are declarative — nothing else needs to notice).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from raytpu.autoscaler.node_provider import (
    NodeGroup,
    NodeGroupSpec,
    NodeProvider,
)

QUEUED = "QUEUED"
REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RUNNING = "RUNNING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"
FAILED = "FAILED"

LIVE_STATES = (QUEUED, REQUESTED, ALLOCATED, RUNNING)


@dataclass
class Instance:
    instance_id: str
    group_type: str
    state: str = QUEUED
    group: Optional[NodeGroup] = None
    idle_since: Optional[float] = None
    # (monotonic ts, new_state, reason) — the v2 audit trail.
    history: List[tuple] = field(default_factory=list)

    def transition(self, state: str, reason: str = "") -> None:
        self.state = state
        self.history.append((time.monotonic(), state, reason))

    @property
    def group_id(self) -> Optional[str]:
        return self.group.group_id if self.group is not None else None


class InstanceManager:
    """Drives ``provider`` toward per-type targets set with
    :meth:`set_target`; every cloud mutation happens inside
    :meth:`reconcile` and is recorded on the instance's history."""

    def __init__(self, provider: NodeProvider,
                 specs: Dict[str, NodeGroupSpec],
                 ray_running_fn: Optional[
                     Callable[[NodeGroup], bool]] = None,
                 max_concurrent_requests: int = 100):
        self.provider = provider
        self.specs = dict(specs)
        # Hook for "the framework is actually up on the slice" (reference:
        # RAY_INSTALLING -> RAY_RUNNING); default: allocation == running.
        self.ray_running_fn = ray_running_fn or (lambda g: True)
        self.max_concurrent_requests = max_concurrent_requests
        self._targets: Dict[str, int] = {n: 0 for n in specs}
        self._instances: Dict[str, Instance] = {}
        # Terminal instances move here so _instances stays bounded while
        # a recent audit trail survives (reference: v2 keeps instance
        # history in storage; a ring suffices for a single head).
        from collections import deque

        self.retired: "deque[Instance]" = deque(maxlen=200)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    # -- declarative surface ------------------------------------------------

    def set_target(self, group_type: str, count: int) -> None:
        if group_type not in self.specs:
            raise KeyError(f"unknown node group type {group_type!r}")
        with self._lock:
            self._targets[group_type] = max(0, int(count))

    def set_targets(self, targets: Dict[str, int]) -> None:
        for name, count in targets.items():
            self.set_target(name, count)

    def instances(self, group_type: Optional[str] = None,
                  states: Optional[Set[str]] = None) -> List[Instance]:
        with self._lock:
            return [i for i in self._instances.values()
                    if (group_type is None or i.group_type == group_type)
                    and (states is None or i.state in states)]

    # -- reconcile ----------------------------------------------------------

    def reconcile(self, busy_group_ids: Optional[Set[str]] = None,
                  idle_timeout_s: float = 0.0,
                  max_launches_per_type=None,
                  poll: bool = True) -> Dict[str, int]:
        """One tick: sync cloud state, repair drift, launch toward
        deficits (bounded; ``max_launches_per_type`` may be an int or a
        per-type dict), retire surplus idle instances. Returns
        create-call counts per type. ``poll=False`` when the caller just
        polled the provider (one cloud list per tick, not two)."""
        busy = busy_group_ids or set()
        if poll:
            self.provider.poll()
        now = time.monotonic()
        launched: Dict[str, int] = {}
        with self._lock:
            self._sync_locked()
            # A busy instance's idle clock resets every tick — not only
            # while a surplus exists — so a stale idle_since from an old
            # surplus episode can never fast-track a just-idle group past
            # idle_timeout_s on a later shrink.
            for inst in self._instances.values():
                if inst.group_id in busy:
                    inst.idle_since = None
            for name, spec in self.specs.items():
                live = [i for i in self._instances.values()
                        if i.group_type == name and i.state in LIVE_STATES]
                want = self._targets.get(name, 0)
                # Queue the FULL deficit (declarative: the desired state
                # exists as QUEUED instances); the launch step below is
                # what rate-limits cloud requests.
                for _ in range(max(0, want - len(live))):
                    inst = Instance(f"i-{next(self._ids)}", name)
                    inst.transition(QUEUED, "target deficit")
                    self._instances[inst.instance_id] = inst
                    live.append(inst)
                if len(live) > want:
                    self._retire_locked(live, want, busy, idle_timeout_s,
                                        now)
            launched = self._launch_locked(max_launches_per_type)
            # Terminal instances leave the working set (bounded memory;
            # reconcile scans stay O(live)).
            for iid in [iid for iid, i in self._instances.items()
                        if i.state in (TERMINATED, FAILED,
                                       ALLOCATION_FAILED)]:
                self.retired.append(self._instances.pop(iid))
        return launched

    # -- internals (all hold self._lock) ------------------------------------

    def _sync_locked(self) -> None:
        """Fold the provider's view into instance states (drift included)."""
        by_gid = {g.group_id: g for g in
                  self.provider.non_terminated_groups()}
        known_gids = {i.group_id for i in self._instances.values()
                      if i.group_id}
        # Adopt externally-created groups so reconcile never fights an
        # operator's manual launches.
        for gid, g in by_gid.items():
            if gid not in known_gids and g.spec.name in self.specs:
                inst = Instance(f"i-{next(self._ids)}", g.spec.name,
                                group=g)
                inst.transition(
                    RUNNING if g.status == "running" else REQUESTED,
                    "adopted existing group")
                self._instances[inst.instance_id] = inst
        for inst in self._instances.values():
            g = by_gid.get(inst.group_id) if inst.group_id else None
            if inst.state == REQUESTED:
                status = (g or inst.group).status
                if status == "running":
                    inst.transition(ALLOCATED, "cloud reports running")
                    if self.ray_running_fn(inst.group):
                        inst.transition(RUNNING, "framework up")
                elif status == "failed":
                    inst.transition(ALLOCATION_FAILED, "provision failed")
                    self._terminate_locked(inst, "cleanup failed launch")
            elif inst.state in (ALLOCATED, RUNNING):
                if g is None or g.status == "failed":
                    # Drift: the cloud lost a slice we believe is live.
                    inst.transition(
                        FAILED, "group vanished" if g is None
                        else "group failed")
                    self._terminate_locked(inst, "cleanup drifted group")

    def _retire_locked(self, live: List[Instance], want: int,
                       busy: Set[str], idle_timeout_s: float,
                       now: float) -> None:
        # Cheapest first: queued (no cloud call yet), then requested,
        # then idle running instances past the timeout.
        for inst in [i for i in live if i.state == QUEUED]:
            if len(live) <= want:
                return
            inst.transition(TERMINATED, "target shrank before launch")
            live.remove(inst)
        for inst in [i for i in live if i.state == REQUESTED]:
            if len(live) <= want:
                return
            inst.transition(TERMINATING, "target shrank mid-launch")
            self._terminate_locked(inst, "target shrank mid-launch")
            live.remove(inst)
        for inst in [i for i in live if i.state in (ALLOCATED, RUNNING)]:
            if len(live) <= want:
                return
            if inst.group_id in busy:
                inst.idle_since = None
                continue
            if inst.idle_since is None:
                inst.idle_since = now
            if now - inst.idle_since >= idle_timeout_s:
                inst.transition(TERMINATING, "surplus idle")
                self._terminate_locked(inst, "surplus idle")
                live.remove(inst)

    def _launch_locked(self, caps=None) -> Dict[str, int]:
        launched: Dict[str, int] = {}
        in_flight = sum(1 for i in self._instances.values()
                        if i.state == REQUESTED)
        for inst in [i for i in self._instances.values()
                     if i.state == QUEUED]:
            if in_flight >= self.max_concurrent_requests:
                break
            if caps is not None:
                cap = (caps.get(inst.group_type)
                       if isinstance(caps, dict) else int(caps))
                if cap is not None and \
                        launched.get(inst.group_type, 0) >= cap:
                    continue
            try:
                inst.group = self.provider.create_node_group(
                    self.specs[inst.group_type])
                inst.transition(REQUESTED, "create requested")
                in_flight += 1
                launched[inst.group_type] = \
                    launched.get(inst.group_type, 0) + 1
            except Exception as e:
                inst.transition(ALLOCATION_FAILED, f"create raised: {e}")
        return launched

    def _terminate_locked(self, inst: Instance, reason: str) -> None:
        try:
            if inst.group is not None:
                self.provider.terminate_node_group(inst.group.group_id)
        except Exception as e:
            inst.transition(FAILED, f"terminate raised: {e}")
            return
        inst.transition(TERMINATED, reason)
        inst.idle_since = None
