"""Node providers: the cloud-facing half of the autoscaler.

Reference analogue: ``python/ray/autoscaler/node_provider.py`` (the
``NodeProvider`` ABC) and the fake in-memory provider used for e2e tests
(``python/ray/autoscaler/_private/fake_multi_node/node_provider.py:237``).

TPU-first difference: the unit of provisioning is a **slice** (node
group), not a single VM. A v4-32 is 4 hosts that exist or die together —
``create_node_group``/``terminate_node_group`` are therefore the primitive
operations, and a group carries its slice topology so the scheduler can
treat it as one ICI domain (reference bolts single-VM TPUs on via
``_private/accelerators/tpu.py``; v2's instance-group abstraction is the
closer shape, ``autoscaler/v2/instance_manager/``).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class NodeGroupSpec:
    """A launchable node-group type (e.g. one TPU slice or one CPU VM)."""

    name: str                      # e.g. "v4-8", "cpu-16"
    hosts: int = 1                 # hosts per group (slice hosts)
    resources_per_host: Dict[str, float] = field(default_factory=dict)
    topology: Optional[tuple] = None  # ICI box, e.g. (2, 2, 1)
    min_groups: int = 0
    max_groups: int = 10

    @property
    def resources_per_group(self) -> Dict[str, float]:
        return {k: v * self.hosts for k, v in
                self.resources_per_host.items()}


@dataclass
class NodeGroup:
    group_id: str
    spec: NodeGroupSpec
    status: str = "pending"        # pending | running | terminated | failed
    host_ids: List[str] = field(default_factory=list)


class NodeProvider:
    """ABC. Implementations talk to GCE/GKE; tests use FakeSliceProvider."""

    def create_node_group(self, spec: NodeGroupSpec) -> NodeGroup:
        raise NotImplementedError

    def terminate_node_group(self, group_id: str) -> None:
        raise NotImplementedError

    def non_terminated_groups(self) -> List[NodeGroup]:
        raise NotImplementedError

    def poll(self) -> None:
        """Advance async provisioning state (cloud polling tick)."""


class FakeSliceProvider(NodeProvider):
    """In-memory provider: groups become ``running`` after
    ``provision_ticks`` polls; supports fault injection via ``fail_next``
    (reference analogue: FakeMultiNodeProvider)."""

    def __init__(self, provision_ticks: int = 1):
        self._lock = threading.Lock()
        self._groups: Dict[str, NodeGroup] = {}
        self._pending_ticks: Dict[str, int] = {}
        self._ids = itertools.count(1)
        self.provision_ticks = provision_ticks
        self.fail_next = 0  # next N creations fail at provision time
        self.create_calls = 0
        self.terminate_calls = 0

    def create_node_group(self, spec: NodeGroupSpec) -> NodeGroup:
        with self._lock:
            gid = f"{spec.name}-{next(self._ids)}"
            group = NodeGroup(gid, spec)
            self._groups[gid] = group
            self._pending_ticks[gid] = self.provision_ticks
            self.create_calls += 1
            return group

    def terminate_node_group(self, group_id: str) -> None:
        with self._lock:
            g = self._groups.get(group_id)
            if g is not None:
                g.status = "terminated"
                g.host_ids = []
                self._pending_ticks.pop(group_id, None)
                self.terminate_calls += 1

    def non_terminated_groups(self) -> List[NodeGroup]:
        with self._lock:
            return [g for g in self._groups.values()
                    if g.status in ("pending", "running")]

    def poll(self) -> None:
        with self._lock:
            for gid, left in list(self._pending_ticks.items()):
                if left > 1:
                    self._pending_ticks[gid] = left - 1
                    continue
                del self._pending_ticks[gid]
                g = self._groups[gid]
                if self.fail_next > 0:
                    self.fail_next -= 1
                    g.status = "failed"
                else:
                    g.status = "running"
                    g.host_ids = [f"{gid}-host{i}"
                                  for i in range(g.spec.hosts)]

    # test helper: simulate a running slice dying under us
    def kill_group(self, group_id: str) -> None:
        with self._lock:
            g = self._groups.get(group_id)
            if g is not None:
                g.status = "failed"
                g.host_ids = []


def _gcloud(args: List[str]) -> str:
    """Default command runner: shells out to the installed gcloud CLI."""
    import subprocess

    out = subprocess.run(["gcloud"] + args, capture_output=True, text=True,
                         timeout=300)
    if out.returncode != 0:
        raise RuntimeError(f"gcloud {' '.join(args[:4])}... failed: "
                           f"{out.stderr.strip()[:500]}")
    return out.stdout


class GceTpuSliceProvider(NodeProvider):
    """**Experimental** — exercised only against a fake gcloud runner in
    CI (this environment has no cloud access); treat the first real
    `gcloud` run as validation, not the tests.

    Real cloud provider: GCE TPU-VM slices via the gcloud CLI
    (reference analogue: ``python/ray/autoscaler/_private/gcp/node_provider``
    + the v2 instance manager's cloud adapters, reshaped around the slice
    as the provisioning unit — a TPU pod slice is one atomic group of
    hosts, exactly what ``gcloud compute tpus tpu-vm create`` provisions).

    ``spec.name`` is the accelerator type (e.g. ``v5litepod-8``,
    ``v4-32``); creation is async and :meth:`poll` reconciles state from
    ``tpu-vm list``. All cloud calls go through a pluggable ``runner``
    (the gcloud CLI by default) so the control logic is testable — and
    auditable — without cloud access.
    """

    _STATE_MAP = {
        "READY": "running",
        "CREATING": "pending",
        "PROVISIONING": "pending",
        "REPAIRING": "pending",
        "STARTING": "pending",
    }

    def __init__(self, project: str, zone: str,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 name_prefix: str = "raytpu",
                 runner=None):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self.name_prefix = name_prefix
        self._run = runner or _gcloud
        self._lock = threading.Lock()
        self._groups: Dict[str, NodeGroup] = {}
        self._ids = itertools.count(1)

    def _scope(self) -> List[str]:
        return [f"--project={self.project}", f"--zone={self.zone}"]

    def create_node_group(self, spec: NodeGroupSpec) -> NodeGroup:
        with self._lock:
            # Skip ids taken by adopted pre-existing groups.
            gid = f"{self.name_prefix}-{spec.name}-{next(self._ids)}"
            while gid in self._groups:
                gid = f"{self.name_prefix}-{spec.name}-{next(self._ids)}"
            group = NodeGroup(gid, spec, status="pending")
            self._groups[gid] = group
        try:
            self._run([
                "compute", "tpus", "tpu-vm", "create", gid,
                *self._scope(),
                f"--accelerator-type={spec.name}",
                f"--version={self.runtime_version}",
                "--async",
            ])
        except Exception:
            # The create never reached the cloud: a phantom 'pending'
            # group would count as in-flight capacity forever (poll keeps
            # absent pending groups pending).
            with self._lock:
                group.status = "failed"
            raise
        return group

    def terminate_node_group(self, group_id: str) -> None:
        with self._lock:
            g = self._groups.get(group_id)
            if g is None or g.status == "terminated":
                return
        # Mark terminated only after the delete is accepted — flipping
        # state first would silently leak a running (billable) slice when
        # gcloud fails, with retries short-circuited by the status check.
        self._run([
            "compute", "tpus", "tpu-vm", "delete", group_id,
            *self._scope(), "--quiet", "--async",
        ])
        with self._lock:
            g.status = "terminated"
            g.host_ids = []

    def non_terminated_groups(self) -> List[NodeGroup]:
        with self._lock:
            return [g for g in self._groups.values()
                    if g.status in ("pending", "running")]

    def poll(self) -> None:
        """Reconcile local state against the cloud's slice list."""
        import json as _json

        out = self._run(["compute", "tpus", "tpu-vm", "list",
                         *self._scope(), "--format=json"])
        listed = {}
        for item in _json.loads(out or "[]"):
            name = item.get("name", "").rsplit("/", 1)[-1]
            listed[name] = item
        with self._lock:
            # Adopt cloud slices this (possibly fresh) provider instance
            # has never seen — a new process running `down` or a re-run
            # `up` must discover existing groups, not ignore them.
            prefix = f"{self.name_prefix}-"
            for name, item in listed.items():
                if name in self._groups or not name.startswith(prefix):
                    continue
                spec_name = name[len(prefix):].rsplit("-", 1)[0]
                hosts = len(item.get("networkEndpoints", [])) or 1
                self._groups[name] = NodeGroup(
                    name, NodeGroupSpec(spec_name, hosts=hosts),
                    status="pending")
            for gid, g in self._groups.items():
                if g.status == "terminated":
                    continue
                item = listed.get(gid)
                if item is None:
                    if g.status != "pending":
                        g.status = "failed"  # slice vanished under us
                        g.host_ids = []
                    continue
                state = self._STATE_MAP.get(item.get("state", ""), "failed")
                g.status = state
                if state == "running":
                    g.host_ids = [
                        ep.get("ipAddress", f"{gid}-host{i}")
                        for i, ep in enumerate(
                            item.get("networkEndpoints", []))
                    ] or [f"{gid}-host{i}" for i in range(g.spec.hosts)]
                else:
                    g.host_ids = []


class K8sSliceProvider(NodeProvider):
    """**Experimental** — exercised against a fake kubectl runner in CI
    (no cluster access in this environment).

    Kubernetes provider (reference analogue: the KubeRay operator's
    worker-group reconciliation + ``_private/kuberay/node_provider.py``,
    reshaped around the slice): one node group = one Pod carrying a TPU
    slice (GKE schedules whole slices onto node pools via
    ``google.com/tpu`` resources + topology selectors). All cluster
    calls go through a pluggable ``runner`` (the kubectl CLI by
    default), so control logic tests need no cluster.

    ``spec.name`` is used as the accelerator selector value (e.g.
    ``tpu-v5-lite-podslice``); the pod template is minimal on purpose —
    production deployments supply their own via ``pod_template``.
    """

    # Succeeded maps to "failed" (a node container exiting is not a
    # requested termination): the reconciler's cleanup then issues the
    # kubectl delete — mapping it to "terminated" would skip the delete
    # (terminate_node_group early-returns) and leak the pod object.
    _PHASE_MAP = {
        "Running": "running",
        "Pending": "pending",
        "Succeeded": "failed",
        "Failed": "failed",
        "Unknown": "failed",
    }

    def __init__(self, namespace: str = "default",
                 image: str = "python:3.12-slim",
                 name_prefix: str = "raytpu",
                 pod_template: Optional[dict] = None,
                 runner=None):
        self.namespace = namespace
        self.image = image
        self.name_prefix = name_prefix
        self.pod_template = pod_template
        self._run = runner or _kubectl
        self._lock = threading.Lock()
        self._groups: Dict[str, NodeGroup] = {}
        self._ids = itertools.count(1)
        # gid -> consecutive polls where a pending pod was absent from
        # the listing. One absence is tolerated (apply -> list race);
        # persistent absence means the pod will never reach Running and
        # the group must fail rather than pend forever.
        self._pending_missing: Dict[str, int] = {}
        self.pending_missing_threshold = 3

    def _pod_manifest(self, gid: str, spec: NodeGroupSpec) -> dict:
        if self.pod_template is not None:
            import copy as _copy

            pod = _copy.deepcopy(self.pod_template)
            meta = pod.setdefault("metadata", {})
            meta["name"] = gid
            # poll() lists by this label — a template without it would
            # never be seen again and the group would pend forever.
            labels = meta.setdefault("labels", {})
            labels["app"] = self.name_prefix
            labels["raytpu-group-type"] = spec.name
            return pod
        tpus = int(spec.resources_per_host.get("TPU", 0))
        limits = {"cpu": str(int(spec.resources_per_host.get("CPU", 1)))}
        if tpus:
            limits["google.com/tpu"] = str(tpus)
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": gid,
                "labels": {"app": self.name_prefix,
                           "raytpu-group-type": spec.name},
            },
            "spec": {
                "restartPolicy": "Never",
                "containers": [{
                    "name": "node",
                    "image": self.image,
                    "resources": {"limits": limits},
                }],
            },
        }
        if tpus:
            pod["spec"]["nodeSelector"] = {
                "cloud.google.com/gke-tpu-accelerator": spec.name,
            }
        return pod

    def create_node_group(self, spec: NodeGroupSpec) -> NodeGroup:
        import json as _json

        with self._lock:
            # Skip ids taken by adopted pre-existing pods.
            gid = f"{self.name_prefix}-{spec.name}-{next(self._ids)}"
            while gid in self._groups:
                gid = f"{self.name_prefix}-{spec.name}-{next(self._ids)}"
            group = NodeGroup(gid, spec, status="pending")
            self._groups[gid] = group
        try:
            self._run(["apply", "-n", self.namespace, "-f", "-"],
                      stdin=_json.dumps(self._pod_manifest(gid, spec)))
        except Exception:
            with self._lock:
                group.status = "failed"
            raise
        return group

    def terminate_node_group(self, group_id: str) -> None:
        with self._lock:
            g = self._groups.get(group_id)
            if g is None or g.status == "terminated":
                return
        # Terminated only after the delete is accepted (same rationale
        # as the GCE provider: never silently leak a running slice).
        self._run(["delete", "pod", group_id, "-n", self.namespace,
                   "--ignore-not-found", "--wait=false"])
        with self._lock:
            g.status = "terminated"
            g.host_ids = []
            self._pending_missing.pop(group_id, None)

    def non_terminated_groups(self) -> List[NodeGroup]:
        with self._lock:
            return [g for g in self._groups.values()
                    if g.status in ("pending", "running")]

    def poll(self) -> None:
        import json as _json

        out = self._run(["get", "pods", "-n", self.namespace,
                         "-l", f"app={self.name_prefix}", "-o", "json"])
        listed = {}
        for item in _json.loads(out or "{}").get("items", []):
            listed[item.get("metadata", {}).get("name", "")] = item
        with self._lock:
            # Adopt labeled pods a fresh provider instance never created
            # (new-process `down`/re-`up` must see existing groups).
            for name, item in listed.items():
                if name in self._groups:
                    continue
                labels = item.get("metadata", {}).get("labels", {})
                spec_name = labels.get("raytpu-group-type") or \
                    name[len(self.name_prefix) + 1:].rsplit("-", 1)[0]
                self._groups[name] = NodeGroup(
                    name, NodeGroupSpec(spec_name), status="pending")
            for gid, g in self._groups.items():
                if g.status == "terminated":
                    continue
                item = listed.get(gid)
                if item is None:
                    if g.status != "pending":
                        g.status = "failed"  # pod vanished under us
                        g.host_ids = []
                    else:
                        n = self._pending_missing.get(gid, 0) + 1
                        self._pending_missing[gid] = n
                        if n >= self.pending_missing_threshold:
                            g.status = "failed"  # never materialized
                            g.host_ids = []
                            del self._pending_missing[gid]
                    continue
                self._pending_missing.pop(gid, None)
                phase = item.get("status", {}).get("phase", "Unknown")
                g.status = self._PHASE_MAP.get(phase, "failed")
                if g.status == "running":
                    # len(host_ids) == spec.hosts is the provider-layer
                    # invariant (the GCE provider pads the same way).
                    ip = item.get("status", {}).get("podIP")
                    g.host_ids = [ip or f"{gid}-host0"] + [
                        f"{gid}-host{i}"
                        for i in range(1, g.spec.hosts)]
                else:
                    g.host_ids = []


def _kubectl(args: List[str], stdin: Optional[str] = None) -> str:
    """Default command runner: shells out to the kubectl CLI."""
    import subprocess

    out = subprocess.run(["kubectl"] + args, capture_output=True,
                         text=True, timeout=120, input=stdin)
    if out.returncode != 0:
        raise RuntimeError(f"kubectl {' '.join(args[:3])}... failed: "
                           f"{out.stderr.strip()[:500]}")
    return out.stdout
