"""Node providers: the cloud-facing half of the autoscaler.

Reference analogue: ``python/ray/autoscaler/node_provider.py`` (the
``NodeProvider`` ABC) and the fake in-memory provider used for e2e tests
(``python/ray/autoscaler/_private/fake_multi_node/node_provider.py:237``).

TPU-first difference: the unit of provisioning is a **slice** (node
group), not a single VM. A v4-32 is 4 hosts that exist or die together —
``create_node_group``/``terminate_node_group`` are therefore the primitive
operations, and a group carries its slice topology so the scheduler can
treat it as one ICI domain (reference bolts single-VM TPUs on via
``_private/accelerators/tpu.py``; v2's instance-group abstraction is the
closer shape, ``autoscaler/v2/instance_manager/``).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class NodeGroupSpec:
    """A launchable node-group type (e.g. one TPU slice or one CPU VM)."""

    name: str                      # e.g. "v4-8", "cpu-16"
    hosts: int = 1                 # hosts per group (slice hosts)
    resources_per_host: Dict[str, float] = field(default_factory=dict)
    topology: Optional[tuple] = None  # ICI box, e.g. (2, 2, 1)
    min_groups: int = 0
    max_groups: int = 10

    @property
    def resources_per_group(self) -> Dict[str, float]:
        return {k: v * self.hosts for k, v in
                self.resources_per_host.items()}


@dataclass
class NodeGroup:
    group_id: str
    spec: NodeGroupSpec
    status: str = "pending"        # pending | running | terminated | failed
    host_ids: List[str] = field(default_factory=list)


class NodeProvider:
    """ABC. Implementations talk to GCE/GKE; tests use FakeSliceProvider."""

    def create_node_group(self, spec: NodeGroupSpec) -> NodeGroup:
        raise NotImplementedError

    def terminate_node_group(self, group_id: str) -> None:
        raise NotImplementedError

    def non_terminated_groups(self) -> List[NodeGroup]:
        raise NotImplementedError

    def poll(self) -> None:
        """Advance async provisioning state (cloud polling tick)."""


class FakeSliceProvider(NodeProvider):
    """In-memory provider: groups become ``running`` after
    ``provision_ticks`` polls; supports fault injection via ``fail_next``
    (reference analogue: FakeMultiNodeProvider)."""

    def __init__(self, provision_ticks: int = 1):
        self._lock = threading.Lock()
        self._groups: Dict[str, NodeGroup] = {}
        self._pending_ticks: Dict[str, int] = {}
        self._ids = itertools.count(1)
        self.provision_ticks = provision_ticks
        self.fail_next = 0  # next N creations fail at provision time
        self.create_calls = 0
        self.terminate_calls = 0

    def create_node_group(self, spec: NodeGroupSpec) -> NodeGroup:
        with self._lock:
            gid = f"{spec.name}-{next(self._ids)}"
            group = NodeGroup(gid, spec)
            self._groups[gid] = group
            self._pending_ticks[gid] = self.provision_ticks
            self.create_calls += 1
            return group

    def terminate_node_group(self, group_id: str) -> None:
        with self._lock:
            g = self._groups.get(group_id)
            if g is not None:
                g.status = "terminated"
                g.host_ids = []
                self._pending_ticks.pop(group_id, None)
                self.terminate_calls += 1

    def non_terminated_groups(self) -> List[NodeGroup]:
        with self._lock:
            return [g for g in self._groups.values()
                    if g.status in ("pending", "running")]

    def poll(self) -> None:
        with self._lock:
            for gid, left in list(self._pending_ticks.items()):
                if left > 1:
                    self._pending_ticks[gid] = left - 1
                    continue
                del self._pending_ticks[gid]
                g = self._groups[gid]
                if self.fail_next > 0:
                    self.fail_next -= 1
                    g.status = "failed"
                else:
                    g.status = "running"
                    g.host_ids = [f"{gid}-host{i}"
                                  for i in range(g.spec.hosts)]

    # test helper: simulate a running slice dying under us
    def kill_group(self, group_id: str) -> None:
        with self._lock:
            g = self._groups.get(group_id)
            if g is not None:
                g.status = "failed"
                g.host_ids = []


def _gcloud(args: List[str]) -> str:
    """Default command runner: shells out to the installed gcloud CLI."""
    import subprocess

    out = subprocess.run(["gcloud"] + args, capture_output=True, text=True,
                         timeout=300)
    if out.returncode != 0:
        raise RuntimeError(f"gcloud {' '.join(args[:4])}... failed: "
                           f"{out.stderr.strip()[:500]}")
    return out.stdout


class GceTpuSliceProvider(NodeProvider):
    """**Experimental** — exercised only against a fake gcloud runner in
    CI (this environment has no cloud access); treat the first real
    `gcloud` run as validation, not the tests.

    Real cloud provider: GCE TPU-VM slices via the gcloud CLI
    (reference analogue: ``python/ray/autoscaler/_private/gcp/node_provider``
    + the v2 instance manager's cloud adapters, reshaped around the slice
    as the provisioning unit — a TPU pod slice is one atomic group of
    hosts, exactly what ``gcloud compute tpus tpu-vm create`` provisions).

    ``spec.name`` is the accelerator type (e.g. ``v5litepod-8``,
    ``v4-32``); creation is async and :meth:`poll` reconciles state from
    ``tpu-vm list``. All cloud calls go through a pluggable ``runner``
    (the gcloud CLI by default) so the control logic is testable — and
    auditable — without cloud access.
    """

    _STATE_MAP = {
        "READY": "running",
        "CREATING": "pending",
        "PROVISIONING": "pending",
        "REPAIRING": "pending",
        "STARTING": "pending",
    }

    def __init__(self, project: str, zone: str,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 name_prefix: str = "raytpu",
                 runner=None):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self.name_prefix = name_prefix
        self._run = runner or _gcloud
        self._lock = threading.Lock()
        self._groups: Dict[str, NodeGroup] = {}
        self._ids = itertools.count(1)

    def _scope(self) -> List[str]:
        return [f"--project={self.project}", f"--zone={self.zone}"]

    def create_node_group(self, spec: NodeGroupSpec) -> NodeGroup:
        with self._lock:
            gid = f"{self.name_prefix}-{spec.name}-{next(self._ids)}"
            group = NodeGroup(gid, spec, status="pending")
            self._groups[gid] = group
        try:
            self._run([
                "compute", "tpus", "tpu-vm", "create", gid,
                *self._scope(),
                f"--accelerator-type={spec.name}",
                f"--version={self.runtime_version}",
                "--async",
            ])
        except Exception:
            # The create never reached the cloud: a phantom 'pending'
            # group would count as in-flight capacity forever (poll keeps
            # absent pending groups pending).
            with self._lock:
                group.status = "failed"
            raise
        return group

    def terminate_node_group(self, group_id: str) -> None:
        with self._lock:
            g = self._groups.get(group_id)
            if g is None or g.status == "terminated":
                return
        # Mark terminated only after the delete is accepted — flipping
        # state first would silently leak a running (billable) slice when
        # gcloud fails, with retries short-circuited by the status check.
        self._run([
            "compute", "tpus", "tpu-vm", "delete", group_id,
            *self._scope(), "--quiet", "--async",
        ])
        with self._lock:
            g.status = "terminated"
            g.host_ids = []

    def non_terminated_groups(self) -> List[NodeGroup]:
        with self._lock:
            return [g for g in self._groups.values()
                    if g.status in ("pending", "running")]

    def poll(self) -> None:
        """Reconcile local state against the cloud's slice list."""
        import json as _json

        out = self._run(["compute", "tpus", "tpu-vm", "list",
                         *self._scope(), "--format=json"])
        listed = {}
        for item in _json.loads(out or "[]"):
            name = item.get("name", "").rsplit("/", 1)[-1]
            listed[name] = item
        with self._lock:
            for gid, g in self._groups.items():
                if g.status == "terminated":
                    continue
                item = listed.get(gid)
                if item is None:
                    if g.status != "pending":
                        g.status = "failed"  # slice vanished under us
                        g.host_ids = []
                    continue
                state = self._STATE_MAP.get(item.get("state", ""), "failed")
                g.status = state
                if state == "running":
                    g.host_ids = [
                        ep.get("ipAddress", f"{gid}-host{i}")
                        for i, ep in enumerate(
                            item.get("networkEndpoints", []))
                    ] or [f"{gid}-host{i}" for i in range(g.spec.hosts)]
                else:
                    g.host_ids = []
