"""Autoscaler: reconciler-style scaling of slice node groups.

Reference analogue: autoscaler v2 (``python/ray/autoscaler/v2/scheduler.py``,
``v2/instance_manager/instance_manager.py:29``) — "what should exist" is
computed from demand (pending resource bundles + min counts), then a
reconciler drives the provider toward it through an instance state machine;
plus v1's bin-packing demand scheduler
(``_private/resource_demand_scheduler.py:102``) for choosing which group
type fits each demand bundle.

Demand sources (the reference reads these from GCS autoscaler state,
``gcs_autoscaler_state_manager.cc``): pending task/actor bundles, pending
placement groups, and per-group ``min_groups``. Slices scale atomically —
a demand of ``{"TPU": 16}`` on v4-8 groups (8 chips/group) provisions two
whole groups.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from raytpu.autoscaler.node_provider import (
    NodeGroup,
    NodeGroupSpec,
    NodeProvider,
)


@dataclass
class ResourceDemand:
    """One pending bundle shape with a count (aggregated demand)."""

    bundle: Dict[str, float]
    count: int = 1


@dataclass
class AutoscalerConfig:
    node_groups: List[NodeGroupSpec] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    max_concurrent_launches: int = 100
    upscaling_speed: float = 1.0  # max new groups = max(5, speed*current)


class StandardAutoscaler:
    """Deterministic core: call :meth:`update` with current demand; it
    launches/terminates through the provider. Drive it from a loop
    (:class:`AutoscalerMonitor`) or directly in tests."""

    def __init__(self, config: AutoscalerConfig, provider: NodeProvider):
        self.config = config
        self.provider = provider
        # v2 core: the declarative reconciler owns every cloud mutation
        # and the per-instance state machine (reference:
        # instance_manager.py:29); this class only computes targets.
        from raytpu.autoscaler.instance_manager import InstanceManager

        self.instance_manager = InstanceManager(
            provider, {s.name: s for s in config.node_groups},
            max_concurrent_requests=config.max_concurrent_launches)
        self._lock = threading.Lock()

    # -- demand → desired groups ------------------------------------------

    def _fits(self, spec: NodeGroupSpec, bundle: Dict[str, float]) -> bool:
        per_group = spec.resources_per_group
        return all(per_group.get(k, 0.0) >= v for k, v in bundle.items())

    def get_desired_groups(
        self, demands: List[ResourceDemand],
        used_groups: Dict[str, int],
    ) -> Dict[str, int]:
        """Bin-pack demand onto group types (first-fit by declaration
        order — reference: ResourceDemandScheduler), respecting min/max."""
        desired: Dict[str, int] = {
            s.name: s.min_groups for s in self.config.node_groups
        }
        # Free capacity on groups we already want (greedy accumulation).
        spare: List[Dict[str, float]] = []
        for spec in self.config.node_groups:
            for _ in range(desired.get(spec.name, 0)):
                spare.append(dict(spec.resources_per_group))

        def place_on_spare(bundle) -> bool:
            for cap in spare:
                if all(cap.get(k, 0.0) >= v for k, v in bundle.items()):
                    for k, v in bundle.items():
                        cap[k] = cap.get(k, 0.0) - v
                    return True
            return False

        def waste_score(spec: NodeGroupSpec, bundle) -> tuple:
            """Best-fit: don't burn a TPU slice on CPU-only demand.
            Primary key: number of resource kinds the group has that the
            bundle doesn't ask for; secondary: leftover requested units."""
            per_group = spec.resources_per_group
            unrequested = sum(1 for k in per_group if k not in bundle)
            leftover = sum(per_group.get(k, 0.0) - v
                           for k, v in bundle.items())
            return (unrequested, leftover)

        for demand in demands:
            for _ in range(demand.count):
                if place_on_spare(demand.bundle):
                    continue
                candidates = [
                    s for s in self.config.node_groups
                    if self._fits(s, demand.bundle)
                    and desired[s.name] < s.max_groups
                ]
                if not candidates:
                    continue  # infeasible demand: surfaced via metrics
                chosen = min(candidates,
                             key=lambda s: waste_score(s, demand.bundle))
                desired[chosen.name] += 1
                cap = dict(chosen.resources_per_group)
                for k, v in demand.bundle.items():
                    cap[k] = cap.get(k, 0.0) - v
                spare.append(cap)
        # Never scale below what's actively used.
        for name, used in used_groups.items():
            if name in desired:
                desired[name] = max(desired[name], used)
        return desired

    # -- reconcile ---------------------------------------------------------

    def update(self, demands: List[ResourceDemand],
               busy_group_ids: Optional[set] = None) -> Dict[str, int]:
        """One reconcile tick: compute per-type targets from demand, hand
        them to the instance manager, reconcile. ``busy_group_ids``:
        groups currently running workloads (never terminated; reset
        their idle clocks)."""
        busy = busy_group_ids or set()
        self.provider.poll()
        groups = self.provider.non_terminated_groups()
        by_type: Dict[str, List[NodeGroup]] = {}
        for g in groups:
            by_type.setdefault(g.spec.name, []).append(g)

        used_counts: Dict[str, int] = {}
        for g in groups:
            if g.group_id in busy:
                used_counts[g.spec.name] = \
                    used_counts.get(g.spec.name, 0) + 1
        desired = self.get_desired_groups(demands, used_counts)

        # Upscaling-speed bound per type (reference: upscaling_speed).
        launch_caps = {
            spec.name: max(5, int(self.config.upscaling_speed *
                                  max(1, len(by_type.get(spec.name, ())))))
            for spec in self.config.node_groups
        }
        with self._lock:
            self.instance_manager.set_targets(desired)
            return self.instance_manager.reconcile(
                busy, idle_timeout_s=self.config.idle_timeout_s,
                max_launches_per_type=launch_caps,
                poll=False)  # polled above, before reading group state


class AutoscalerMonitor:
    """Background loop wiring a cluster head's demand feed to the
    autoscaler (reference: ``autoscaler/_private/monitor.py``)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 demand_fn, busy_fn=None, period_s: float = 1.0):
        self.autoscaler = autoscaler
        self.demand_fn = demand_fn
        self.busy_fn = busy_fn or (lambda: set())
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler-monitor",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        import logging

        log = logging.getLogger("raytpu.autoscaler")
        while not self._stop.wait(self.period_s):
            try:
                self.autoscaler.update(self.demand_fn(), self.busy_fn())
            except Exception:
                log.exception("autoscaler update failed")

    def stop(self) -> None:
        self._stop.set()
