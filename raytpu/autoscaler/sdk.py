"""Autoscaler SDK: programmatic demand hints.

Reference analogue: ``python/ray/autoscaler/sdk.py`` —
``request_resources(num_cpus=..., bundles=[...])`` tells the autoscaler
to scale to hold the given shapes immediately, without waiting for
tasks to queue. Each call replaces the previous request; calling with
nothing withdraws it.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[List[Dict[str, float]]] = None
                      ) -> int:
    """Ask the autoscaler to provision capacity for these bundles now.

    Returns the number of bundles recorded. Cluster mode only (the
    hint lives on the head, where the autoscaler reads demand); in
    local mode this is a no-op returning 0 — there is no cloud to
    scale.
    """
    from raytpu.runtime import api

    if api._backend is None:
        raise RuntimeError("raytpu is not initialized")
    payload: List[Dict[str, float]] = []
    if num_cpus:
        # Reference semantics: N one-CPU bundles, not one N-CPU bundle —
        # the demand must pack across node shapes, not require a single
        # host with N cpus.
        payload.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    for b in bundles or []:
        payload.append({str(k): float(v) for k, v in b.items()})
    head = getattr(api._backend, "_head", None)
    if head is None:
        return 0  # local backend: nothing to scale
    return int(head.call("request_resources", payload))


__all__ = ["request_resources"]
