"""raytpu.state — cluster introspection (reference: python/ray/util/state/)."""

from raytpu.state.api import (
    get_request_timeline,
    get_timeline,
    list_actors,
    list_events,
    list_nodes,
    list_metric_series,
    list_objects,
    list_placement_groups,
    list_serve_requests,
    list_tasks,
    object_summary,
    query_metrics,
    summarize_tasks,
    summary_actors,
    summary_tasks,
)

__all__ = [
    "get_request_timeline", "get_timeline", "list_actors", "list_events",
    "list_metric_series", "list_nodes", "list_objects",
    "list_placement_groups", "list_serve_requests", "list_tasks",
    "object_summary", "query_metrics", "summarize_tasks", "summary_actors",
    "summary_tasks",
]
