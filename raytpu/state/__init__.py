"""raytpu.state — cluster introspection (reference: python/ray/util/state/)."""

from raytpu.state.api import (
    list_actors,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    object_summary,
    summarize_tasks,
)

__all__ = [
    "list_actors", "list_nodes", "list_objects", "list_placement_groups",
    "list_tasks", "object_summary", "summarize_tasks",
]
