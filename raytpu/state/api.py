"""Cluster introspection: the state API.

Reference analogue: ``python/ray/util/state/api.py`` (``ray list actors /
tasks / objects / nodes / placement-groups`` and summaries) backed by the
GCS task-event store (``GcsTaskManager``). Ours reads two planes and
merges them:

- **Live tables** — single-process mode inspects the local scheduler's
  tables directly; cluster mode aggregates the head's directories plus
  each node's ``debug_state``.
- **The flight recorder** (:mod:`raytpu.util.task_events`) — lifecycle
  timelines for finished/failed/retried entities that live tables have
  already forgotten. Cluster mode queries the head's
  :class:`~raytpu.util.task_events.TaskEventStore`; local mode folds the
  in-process ring on demand.

``detail=True`` attaches the per-entity event timeline (ts-sorted), and
``state``/``node``/``name`` filter server-side so a busy head ships only
the rows asked for.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def _backend():
    from raytpu.runtime import api

    if api._backend is None:
        raise RuntimeError("raytpu is not initialized")
    return api._backend


def _is_cluster(b) -> bool:
    return type(b).__name__ == "ClusterBackend"


# -- flight-recorder plumbing -------------------------------------------------


def _local_store():
    """Fold the in-process event ring into a throwaway store (local mode
    has no head to ship to — the ring IS the record)."""
    from raytpu.util import task_events

    store = task_events.TaskEventStore()
    store.add_batch(task_events.get_events())
    return store


def _recorder_list(kind: str, state: Optional[str] = None,
                   node: Optional[str] = None, name: Optional[str] = None,
                   limit: int = 1000,
                   detail: bool = False) -> Optional[List[dict]]:
    """Flight-recorder records for ``kind``; None when unavailable
    (recorder never armed locally, or head unreachable)."""
    b = _backend()
    if _is_cluster(b):
        try:
            return b._head.call("state_list", kind, state, node, name,
                                limit, detail)
        except Exception:
            return None
    from raytpu.util import task_events

    if not task_events.ship_enabled() and not task_events.get_events():
        return None
    return _local_store().list(kind, state=state, node=node, name=name,
                               limit=limit, detail=detail)


def _norm_task(rec: dict) -> Dict[str, Any]:
    """Recorder record → the state-API task row shape."""
    out: Dict[str, Any] = {
        "task_id": rec.get("id"),
        "name": rec.get("name"),
        "state": rec.get("state"),
        "node_id": rec.get("node_id"),
        "attempt": rec.get("attempt", 0),
        "num_events": rec.get("num_events", 0),
        "first_ts": rec.get("first_ts"),
        "last_ts": rec.get("last_ts"),
    }
    for k in ("error", "trace_id", "parent_task_id", "worker_id"):
        if rec.get(k):
            out[k] = rec[k]
    if "events" in rec:
        out["events"] = rec["events"]
    return out


def _match(row: dict, state: Optional[str], node: Optional[str],
           name: Optional[str]) -> bool:
    if state is not None and row.get("state") != state:
        return False
    if node and not str(row.get("node_id") or "").startswith(node):
        return False
    if name and name not in str(row.get("name") or ""):
        return False
    return True


# -- listings -----------------------------------------------------------------


def list_nodes(detail: bool = False) -> List[Dict[str, Any]]:
    import raytpu

    nodes = raytpu.nodes()
    if detail:
        recs = _recorder_list("node", limit=0, detail=True) or []
        by_id = {r.get("id"): r for r in recs}
        for n in nodes:
            rec = by_id.get(n.get("node_id"))
            if rec:
                n["events"] = rec.get("events", [])
    return nodes


def list_actors(state: Optional[str] = None, node: Optional[str] = None,
                name: Optional[str] = None,
                detail: bool = False) -> Dict[str, Any]:
    """Actors across the cluster. Returns ``{"actors": [...],
    "partial": bool, "errors": [{"node_id", "error"}, ...]}`` — an
    unreachable node marks the listing partial instead of silently
    shrinking it (reference: the state API's warn-on-partial-response
    behavior in ``util/state/api.py``)."""
    b = _backend()
    errors: List[Dict[str, Any]] = []
    actors: List[Dict[str, Any]] = []
    if _is_cluster(b):
        try:
            nodes = b._head.call("list_nodes")
        except Exception as e:
            return {"actors": [], "partial": True,
                    "errors": [{"node_id": "head",
                                "error": f"{type(e).__name__}: {e}"}]}
        for info in nodes:
            if not info["alive"] or info["labels"].get("role") == "driver":
                continue
            try:
                st = b._peer(info["address"]).call("debug_state")
            except Exception as e:
                errors.append({"node_id": info["node_id"],
                               "error": f"{type(e).__name__}: {e}"})
                continue
            recs = st.get("actor_records")
            if recs is None:
                # Old daemon: only compact id prefixes are available.
                recs = [{"actor_id": aid, "name": None, "state": "ALIVE",
                         "pending_tasks": None}
                        for aid in st.get("actors", ())]
            for rec in recs:
                actors.append({**rec, "node_id": info["node_id"]})
    else:
        with b._lock:
            actors = [
                {
                    "actor_id": aid.hex(),
                    "name": rt.name,
                    "state": "DEAD" if rt.dead else "ALIVE",
                    "max_concurrency": rt.max_concurrency,
                    "detached": rt.detached,
                    "pending_tasks": rt.queue.qsize(),
                    "node_id": b.node_id.hex(),
                }
                for aid, rt in b._actors.items()
            ]
    actors = [a for a in actors if _match(a, state, node, name)]
    if detail:
        recs = _recorder_list("actor", limit=0, detail=True) or []
        by_id = {r.get("id"): r for r in recs}
        for a in actors:
            rec = by_id.get(a.get("actor_id"))
            if rec:
                a["events"] = rec.get("events", [])
    return {"actors": actors, "partial": bool(errors), "errors": errors}


def list_tasks(state: Optional[str] = None, node: Optional[str] = None,
               name: Optional[str] = None, detail: bool = False,
               limit: int = 1000) -> List[Dict[str, Any]]:
    """Tasks: flight-recorder records (full lifecycle, survives task
    completion) merged with the live scheduling tables (covers the
    recorder-disabled case and queue states the store may lag on)."""
    b = _backend()
    live: List[Dict[str, Any]] = []
    if _is_cluster(b):
        with b._lock:
            for rec in b._inflight.values():
                live.append({"task_id": rec.spec.task_id.hex(),
                             "name": rec.spec.name,
                             "state": "RUNNING_OR_PENDING_NODE",
                             "node_id": rec.node_id})
            for spec in b._pending:
                live.append({"task_id": spec.task_id.hex(),
                             "name": spec.name,
                             "state": "PENDING_SCHEDULING",
                             "node_id": None})
    else:
        with b._lock:
            live = [
                {
                    "task_id": tid.hex(),
                    "name": rec.spec.name,
                    "state": rec.state.upper(),
                    "attempt": rec.spec.attempt,
                    "missing_deps": len(rec.missing_deps),
                }
                for tid, rec in b._tasks.items()
            ]
            seen_live = {t["task_id"] for t in live}
            # Finished tasks live on in the event buffer (reference:
            # finished tasks come from the GcsTaskManager event store,
            # not live tables).
            latest: Dict[str, dict] = {}
            for ev in b._task_events:
                latest[ev["task_id"]] = ev
            for tid, ev in latest.items():
                if tid not in seen_live:
                    live.append({
                        "task_id": tid,
                        "name": ev.get("name"),
                        "state": ev.get("state", "finished").upper(),
                        "attempt": 0,
                        "missing_deps": 0,
                    })
    live = [t for t in live if _match(t, state, node, name)]
    recorded = _recorder_list("task", state=state, node=node, name=name,
                              limit=limit, detail=detail)
    if recorded is None:
        return live[:limit] if limit else live
    out = [_norm_task(r) for r in recorded]
    have = {t["task_id"] for t in out}
    out.extend(t for t in live if t["task_id"] not in have)
    return out[:limit] if limit else out


def list_objects(detail: bool = False) -> List[Dict[str, Any]]:
    b = _backend()
    store = b.store
    with store._cv:
        entries = [
            {"object_id": oid.hex(), "size_bytes": sv.total_bytes()}
            for oid, sv in store._objects.items()
        ]
    if detail:
        recs = _recorder_list("object", limit=0, detail=True) or []
        by_id = {r.get("id"): r for r in recs}
        for e in entries:
            rec = by_id.get(e["object_id"])
            if rec:
                e["events"] = rec.get("events", [])
    return entries


def list_placement_groups() -> List[Dict[str, Any]]:
    b = _backend()
    with b._lock:
        pgs = dict(b._pgs)
    out = []
    for pg_id, pg in pgs.items():
        if isinstance(pg, dict):  # cluster backend caches dicts
            out.append({"placement_group_id": pg_id.hex(), **{
                k: v for k, v in pg.items() if k != "bundles"},
                "bundles": pg["bundles"]})
        else:
            out.append({
                "placement_group_id": pg_id.hex(),
                "state": pg.state,
                "strategy": pg.strategy,
                "bundles": [x.resources.to_dict() for x in pg.bundles
                            if x is not None],
            })
    return out


def list_events(severity: Optional[str] = None,
                label: Optional[str] = None,
                limit: int = 200) -> List[Dict[str, Any]]:
    """Structured operational events (reference: ``ray list
    cluster-events`` over the dashboard event module)."""
    b = _backend()
    if _is_cluster(b):
        return b._head.call("list_events", severity, label, limit)
    from raytpu.util import events

    if int(limit) <= 0:
        return []
    return events.recent_events(severity, label)[-int(limit):]


# -- metrics ------------------------------------------------------------------


_local_mstore = None


def _local_metric_store():
    """Single-process mode has no head TSDB; fold the in-process metric
    registry's pending delta frames into a module-lifetime store so
    repeated queries see accumulated history, not just the last delta."""
    global _local_mstore
    from raytpu.util import metrics
    from raytpu.util import tsdb

    if _local_mstore is None:
        _local_mstore = tsdb.MetricStore()
    metrics.collect(force=True)
    frames, dropped = metrics.drain()
    if dropped:
        _local_mstore.note_upstream_drops(dropped)
    if frames:
        _local_mstore.push(frames)
    return _local_mstore


def query_metrics(name: str, tags: Optional[Dict[str, str]] = None,
                  agg: str = "sum", since_s: float = 600.0,
                  step: Optional[float] = None) -> Optional[Dict[str, Any]]:
    """Aggregate one metric across the cluster from the head TSDB
    (``{"name", "kind", "agg", "step", "series_matched", "points"}``).
    Local mode folds the in-process registry; ``None`` when the head is
    unreachable."""
    b = _backend()
    if _is_cluster(b):
        try:
            return b._head.call("metrics_query", name, tags, agg,
                                since_s, step)
        except Exception:
            return None
    return _local_metric_store().query(name, tags=tags, agg=agg,
                                       since_s=since_s, step=step)


def list_metric_series(prefix: Optional[str] = None) -> \
        Optional[List[Dict[str, Any]]]:
    """Every live series (name, tags, kind) the head TSDB currently
    holds, optionally filtered by name prefix."""
    b = _backend()
    if _is_cluster(b):
        try:
            return b._head.call("metrics_series", prefix)
        except Exception:
            return None
    return _local_metric_store().series(prefix)


def rpc_stage_summary(since_s: float = 600.0) -> Dict[str, Any]:
    """Per-stage RPC dispatch timing — recv/decode/queue/handler/encode/
    send p50/p95 seconds from ``raytpu_rpc_stage_seconds``, grouped
    ``{method: {stage: {"p50", "p95"}}}``. Empty until a process with
    ``RAYTPU_PROFILE_CONTINUOUS=1`` has served RPCs (the histogram only
    moves while stage timing is armed)."""
    series = list_metric_series("raytpu_rpc_stage_seconds") or []
    combos = sorted({(s["tags"].get("stage", ""),
                      s["tags"].get("method", "")) for s in series})
    out: Dict[str, Any] = {}
    for stage, method in combos:
        if not stage:
            continue
        tags = {"stage": stage, "method": method}
        row: Dict[str, Any] = {}
        for q in ("p50", "p95"):
            res = query_metrics("raytpu_rpc_stage_seconds", tags=tags,
                                agg=q, since_s=since_s)
            pts = [p for p in (res or {}).get("points") or []
                   if p[1] is not None]
            row[q] = pts[-1][1] if pts else None
        out.setdefault(method, {})[stage] = row
    return out


# -- summaries & timelines ----------------------------------------------------


def _recorder_summary(kind: str) -> Dict[str, Any]:
    b = _backend()
    if _is_cluster(b):
        try:
            return b._head.call("state_summary", kind)
        except Exception as e:
            return {"kind": kind, "total": 0, "by_state": {},
                    "error": f"{type(e).__name__}: {e}"}
    return _local_store().summary(kind)


def summary_tasks() -> Dict[str, Any]:
    """Counts by state × function name plus queue→run latency
    percentiles from SUBMITTED→RUNNING event deltas (the ``ray summary
    tasks`` shape)."""
    return _recorder_summary("task")


def summary_actors() -> Dict[str, Any]:
    return _recorder_summary("actor")


def get_timeline(entity_id: str, kind: str = "task") -> Optional[dict]:
    """One entity's full lifecycle record (ts-sorted events, attempt
    numbers, trace-id cross-link). Accepts a unique id prefix."""
    b = _backend()
    if _is_cluster(b):
        try:
            return b._head.call("state_timeline", entity_id, kind)
        except Exception:
            return None
    return _local_store().get(kind, entity_id)


def get_request_timeline(request_id: str) -> Optional[dict]:
    """One serve request's stitched lifecycle waterfall: every
    RECEIVED→…→FINISHED/ABORTED/FAILED transition any process emitted
    under this id, ts-sorted, with deployment/tenant attribution.
    Accepts a unique id prefix (what a CLI user pastes)."""
    return get_timeline(request_id, kind="request")


def list_serve_requests(deployment: Optional[str] = None,
                        tenant: Optional[str] = None,
                        state: Optional[str] = None,
                        limit: int = 100,
                        detail: bool = False) -> List[Dict[str, Any]]:
    """Serve request records from the flight recorder, newest first,
    filtered by deployment/tenant/lifecycle state."""
    recs = _recorder_list("request", state=state, limit=0,
                          detail=detail) or []
    out = []
    for r in recs:
        if deployment and r.get("deployment") != deployment:
            continue
        if tenant and r.get("tenant") != tenant:
            continue
        out.append(r)
    return out[:max(0, int(limit))] if limit else out


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for t in list_tasks():
        counts[t["state"]] = counts.get(t["state"], 0) + 1
    return counts


def object_summary() -> Dict[str, Any]:
    objs = list_objects()
    return {
        "count": len(objs),
        "total_bytes": sum(o["size_bytes"] for o in objs),
    }
