"""Cluster introspection: the state API.

Reference analogue: ``python/ray/util/state/api.py`` (``ray list actors /
tasks / objects / nodes / placement-groups`` and summaries) backed by the
GCS task-event store (``GcsTaskManager``). Ours reads the live backend:
single-process mode inspects the local scheduler's tables directly;
cluster mode aggregates the head's directories plus each node's
``debug_state``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def _backend():
    from raytpu.runtime import api

    if api._backend is None:
        raise RuntimeError("raytpu is not initialized")
    return api._backend


def _is_cluster(b) -> bool:
    return type(b).__name__ == "ClusterBackend"


def list_nodes() -> List[Dict[str, Any]]:
    import raytpu

    return raytpu.nodes()


def list_actors() -> List[Dict[str, Any]]:
    b = _backend()
    if _is_cluster(b):
        out = []
        for info in b._head.call("list_nodes"):
            if not info["alive"] or info["labels"].get("role") == "driver":
                continue
            try:
                st = b._peer(info["address"]).call("debug_state")
            except Exception:
                continue
            for aid in st.get("actors", ()):
                out.append({"actor_id": aid, "node_id": info["node_id"],
                            "state": "ALIVE"})
        return out
    with b._lock:
        return [
            {
                "actor_id": aid.hex(),
                "name": rt.name,
                "state": "DEAD" if rt.dead else "ALIVE",
                "max_concurrency": rt.max_concurrency,
                "detached": rt.detached,
                "pending_tasks": rt.queue.qsize(),
            }
            for aid, rt in b._actors.items()
        ]


def list_tasks(state: Optional[str] = None) -> List[Dict[str, Any]]:
    b = _backend()
    if _is_cluster(b):
        out = []
        with b._lock:
            for rec in b._inflight.values():
                out.append({"task_id": rec.spec.task_id.hex(),
                            "name": rec.spec.name,
                            "state": "RUNNING_OR_PENDING_NODE",
                            "node_id": rec.node_id})
            for spec in b._pending:
                out.append({"task_id": spec.task_id.hex(),
                            "name": spec.name,
                            "state": "PENDING_SCHEDULING",
                            "node_id": None})
        return [t for t in out if state is None or t["state"] == state]
    with b._lock:
        out = [
            {
                "task_id": tid.hex(),
                "name": rec.spec.name,
                "state": rec.state.upper(),
                "attempt": rec.spec.attempt,
                "missing_deps": len(rec.missing_deps),
            }
            for tid, rec in b._tasks.items()
        ]
        live = {t["task_id"] for t in out}
        # Finished tasks live on in the event buffer (reference: finished
        # tasks come from the GcsTaskManager event store, not live tables).
        latest: Dict[str, dict] = {}
        for ev in b._task_events:
            latest[ev["task_id"]] = ev
        for tid, ev in latest.items():
            if tid not in live:
                out.append({
                    "task_id": tid,
                    "name": ev.get("name"),
                    "state": ev.get("state", "finished").upper(),
                    "attempt": 0,
                    "missing_deps": 0,
                })
    return [t for t in out if state is None or t["state"] == state]


def list_objects() -> List[Dict[str, Any]]:
    b = _backend()
    store = b.store
    with store._cv:
        entries = [
            {"object_id": oid.hex(), "size_bytes": sv.total_bytes()}
            for oid, sv in store._objects.items()
        ]
    return entries


def list_placement_groups() -> List[Dict[str, Any]]:
    b = _backend()
    with b._lock:
        pgs = dict(b._pgs)
    out = []
    for pg_id, pg in pgs.items():
        if isinstance(pg, dict):  # cluster backend caches dicts
            out.append({"placement_group_id": pg_id.hex(), **{
                k: v for k, v in pg.items() if k != "bundles"},
                "bundles": pg["bundles"]})
        else:
            out.append({
                "placement_group_id": pg_id.hex(),
                "state": pg.state,
                "strategy": pg.strategy,
                "bundles": [x.resources.to_dict() for x in pg.bundles
                            if x is not None],
            })
    return out


def list_events(severity: Optional[str] = None,
                label: Optional[str] = None,
                limit: int = 200) -> List[Dict[str, Any]]:
    """Structured operational events (reference: ``ray list
    cluster-events`` over the dashboard event module)."""
    b = _backend()
    if _is_cluster(b):
        return b._head.call("list_events", severity, label, limit)
    from raytpu.util import events

    if int(limit) <= 0:
        return []
    return events.recent_events(severity, label)[-int(limit):]


def summarize_tasks() -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for t in list_tasks():
        counts[t["state"]] = counts.get(t["state"], 0) + 1
    return counts


def object_summary() -> Dict[str, Any]:
    objs = list_objects()
    return {
        "count": len(objs),
        "total_bytes": sum(o["size_bytes"] for o in objs),
    }
