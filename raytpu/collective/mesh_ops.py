"""Device-plane collectives: XLA ops over mesh axes.

This is the TPU-native replacement for the reference's NCCL backend
(``python/ray/util/collective/collective_group/nccl_collective_group.py``):
instead of host-initiated communicator calls, collectives are *ops inside
compiled programs* over a ``jax.sharding.Mesh`` — XLA schedules them onto
ICI links and overlaps them with compute. Use these inside
``jax.shard_map`` (or any pjit-traced function with a bound axis).

Each wrapper matches the host-plane API name so strategy code can be
written once against either plane.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def allreduce(x, axis_name: str, op: str = "sum"):
    """psum/pmax/pmin/pmean over a mesh axis (ICI ring or torus all-reduce,
    chosen by XLA from topology)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    raise ValueError(f"unsupported in-mesh reduce op {op!r}")


def allgather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reducescatter(x, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def broadcast(x, axis_name: str, src_rank: int = 0):
    """Every shard gets src_rank's value: select src's contribution via a
    masked psum (single collective; XLA lowers to an ICI broadcast)."""
    idx = lax.axis_index(axis_name)
    mask = (idx == src_rank).astype(x.dtype)
    return lax.psum(x * mask, axis_name)


def ppermute(x, axis_name: str, perm: Sequence[tuple]):
    return lax.ppermute(x, axis_name, perm=perm)


def send_next(x, axis_name: str, world: int):
    """Ring shift by +1 along the axis (the ring-attention building block)."""
    perm = [(i, (i + 1) % world) for i in range(world)]
    return lax.ppermute(x, axis_name, perm=perm)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    """The Ulysses primitive: resharding between sequence- and head-sharded
    layouts rides a single ICI all-to-all."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def barrier(axis_name: str):
    """A cheap synchronization point: psum of a unit scalar."""
    return lax.psum(jnp.ones((), jnp.int32), axis_name)


def group_call(mesh: Mesh, fn: Callable, *args,
               in_specs=None, out_specs=None, check_vma: bool = False):
    """Run ``fn`` SPMD over ``mesh`` with the wrappers above bound to the
    mesh's axis names — the moral equivalent of the reference's
    "declare a collective group over these actors, then call collectives"
    flow (``collective.py:151``), collapsed into one compiled program.
    """
    from jax import shard_map

    if in_specs is None:
        in_specs = P(*mesh.axis_names)
    if out_specs is None:
        out_specs = P(*mesh.axis_names)
    wrapped = shard_map(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=check_vma)
    return wrapped(*args)
