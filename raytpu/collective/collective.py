"""Host-plane collective communication between tasks/actors.

Reference analogue: ``ray.util.collective``
(``python/ray/util/collective/collective.py`` — ``init_collective_group``
``:120``, ``create_collective_group`` ``:151``, ``allreduce`` ``:258``,
``broadcast`` ``:373``, ``allgather`` ``:423``, ``reducescatter`` ``:472``,
``send`` ``:531``, ``recv`` ``:594``). The reference offers NCCL and GLOO
backends; on TPU the heavy-tensor plane is *inside* compiled XLA programs
(see :mod:`raytpu.collective.mesh_ops`), so this module is the analogue of
the GLOO backend only: host-side, small-tensor, numpy-based collectives for
orchestration-level exchange (rendezvous metadata, eval metrics, parameter
broadcast to env-runners, ...).

Rendezvous follows the reference's named-actor pattern
(``NCCLUniqueIDStore``, ``python/ray/util/collective/util.py:9``): ranks
meet at a named coordinator actor per group; each collective op is a
monotonically-sequenced slot on that actor, ranks post contributions and
poll for the completed result.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda arrs: np.sum(arrs, axis=0),
    ReduceOp.PRODUCT: lambda arrs: np.prod(arrs, axis=0),
    ReduceOp.MIN: lambda arrs: np.min(arrs, axis=0),
    ReduceOp.MAX: lambda arrs: np.max(arrs, axis=0),
}


class _CollectiveError:
    """Poison-pill slot result: delivered to every polling rank."""

    def __init__(self, message: str):
        self.message = message


class _Coordinator:
    """Named per-group rendezvous + collective slots.

    Runs as a raytpu actor. Methods never block, so the default sequential
    actor queue cannot deadlock; ranks poll (reference gloo groups spin on
    a store too, just below the user API).
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        # op slots: seq -> {"parts": {rank: payload}, "result": Any}
        self._slots: Dict[int, dict] = {}
        # point-to-point mailboxes: (src, dst, seq) -> payload
        self._mail: Dict[tuple, Any] = {}
        self._joined: set = set()

    def join(self, rank: int) -> int:
        self._joined.add(rank)
        return self.world_size

    def joined_count(self) -> int:
        return len(self._joined)

    def post(self, seq: int, rank: int, op: str, payload):
        slot = self._slots.setdefault(seq, {"parts": {}, "op": op,
                                            "result": None, "taken": set()})
        if slot["op"] != op:
            # Poison the slot so every rank (including ones already
            # polling) observes the mismatch instead of hanging.
            slot["result"] = _CollectiveError(
                f"collective op mismatch at seq {seq}: rank {rank} called "
                f"{op!r} but group is in {slot['op']!r} — collective calls "
                "must be issued in the same order on every rank")
            raise ValueError(slot["result"].message)
        slot["parts"][rank] = payload
        if len(slot["parts"]) == self.world_size:
            slot["result"] = self._complete(slot)

    def poll(self, seq: int, rank: int):
        """Returns (done, result). Frees the slot once every rank took it."""
        slot = self._slots.get(seq)
        if slot is None or slot["result"] is None:
            return False, None
        result = slot["result"]
        out = result[rank] if isinstance(result, dict) else result
        slot["taken"].add(rank)
        if len(slot["taken"]) == self.world_size:
            del self._slots[seq]
        return True, out

    def p2p_send(self, src: int, dst: int, seq: int, payload):
        self._mail[(src, dst, seq)] = payload

    def p2p_recv(self, src: int, dst: int, seq: int):
        key = (src, dst, seq)
        if key in self._mail:
            return True, self._mail.pop(key)
        return False, None

    def _complete(self, slot: dict):
        op = slot["op"]
        parts = slot["parts"]
        ordered = [parts[r] for r in range(self.world_size)]
        if op.startswith("allreduce:"):
            return _REDUCERS[op.split(":", 1)[1]](np.stack(ordered))
        if op == "allgather":
            return list(ordered)
        if op.startswith("reducescatter:"):
            red = _REDUCERS[op.split(":", 1)[1]](np.stack(ordered))
            chunks = np.array_split(red, self.world_size, axis=0)
            return {r: chunks[r] for r in range(self.world_size)}
        if op.startswith("broadcast:"):
            src = int(op.split(":", 1)[1])
            return parts[src]
        if op == "barrier":
            return True
        raise ValueError(f"unknown collective op {op!r}")


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, handle):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.handle = handle
        self.seq = 0
        self.p2p_seq: Dict[tuple, int] = {}


_local = threading.local()


def _reset_thread_groups() -> None:
    """Task-scope reset: execution threads are reused across tasks; a
    group one task joined must not look initialized to the next task on
    the same thread (stale rank/coordinator -> wrong reductions)."""
    if hasattr(_local, "groups"):
        del _local.groups


try:
    from raytpu.runtime import context as _ctx_mod

    _ctx_mod.register_task_scope_reset(_reset_thread_groups)
except Exception:  # pragma: no cover — import-order safety
    pass


def _groups() -> Dict[str, _GroupState]:
    if not hasattr(_local, "groups"):
        _local.groups = {}
    return _local.groups


def _coordinator_name(group_name: str) -> str:
    return f"raytpu::collective::{group_name}"


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default") -> None:
    """Join (creating if first) the collective group ``group_name``.

    Must be called by every participating task/actor with a distinct
    ``rank`` in ``[0, world_size)``. Reference:
    ``python/ray/util/collective/collective.py:120``.

    ``backend``: only ``"host"`` here. Device-plane collectives live inside
    compiled programs (:mod:`raytpu.collective.mesh_ops`) and need no group.
    """
    import raytpu

    if backend not in ("host", "gloo"):
        raise ValueError(
            f"backend {backend!r} unsupported; host-plane collectives use "
            "'host' — device tensors should use in-mesh XLA collectives "
            "(raytpu.collective.mesh_ops)")
    if not (0 <= rank < world_size):
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    name = _coordinator_name(group_name)
    coord_cls = raytpu.remote(_Coordinator)
    try:
        handle = coord_cls.options(
            name=name, lifetime="detached", num_cpus=0,
        ).remote(world_size)
    except ValueError:
        handle = raytpu.get_actor(name)
    ws = raytpu.get(handle.join.remote(rank))
    if ws != world_size:
        raise ValueError(
            f"group {group_name!r} exists with world_size={ws}, "
            f"got {world_size}")
    _groups()[group_name] = _GroupState(group_name, world_size, rank, handle)


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups()


def get_rank(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return -1 if g is None else g.rank


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return -1 if g is None else g.world_size


def destroy_collective_group(group_name: str = "default") -> None:
    _groups().pop(group_name, None)


def _group(group_name: str) -> _GroupState:
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized on this "
            "worker; call init_collective_group() first")
    return g


def _run_collective(g: _GroupState, op: str, payload,
                    timeout: Optional[float] = None):
    import raytpu

    seq = g.seq
    g.seq += 1
    raytpu.get(g.handle.post.remote(seq, g.rank, op, payload))
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        done, result = raytpu.get(g.handle.poll.remote(seq, g.rank))
        if done:
            if isinstance(result, _CollectiveError):
                raise RuntimeError(result.message)
            return result
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(f"collective {op} seq={seq} timed out")
        time.sleep(0.002)


def _as_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


def allreduce(tensor, group_name: str = "default",
              op: str = ReduceOp.SUM) -> np.ndarray:
    """All-reduce ``tensor`` across the group; returns the reduced array.

    Reference mutates in place (``collective.py:258``); we return the value
    (functional, like everything JAX-side) and copy into ``tensor`` when it
    is a writable ndarray for drop-in parity.
    """
    g = _group(group_name)
    result = _run_collective(g, f"allreduce:{op}", _as_numpy(tensor))
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
    return result


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    g = _group(group_name)
    return _run_collective(g, "allgather", _as_numpy(tensor))


def reducescatter(tensor, group_name: str = "default",
                  op: str = ReduceOp.SUM) -> np.ndarray:
    """Reduce across ranks, then scatter row-chunks: rank r gets chunk r
    of axis 0 (reference: ``collective.py:472``)."""
    g = _group(group_name)
    return _run_collective(g, f"reducescatter:{op}", _as_numpy(tensor))


def broadcast(tensor, src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    g = _group(group_name)
    result = _run_collective(g, f"broadcast:{src_rank}", _as_numpy(tensor))
    if isinstance(tensor, np.ndarray) and tensor.flags.writeable:
        np.copyto(tensor, result)
    return result


def barrier(group_name: str = "default",
            timeout: Optional[float] = None) -> None:
    g = _group(group_name)
    _run_collective(g, "barrier", None, timeout=timeout)


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    import raytpu

    g = _group(group_name)
    key = (g.rank, dst_rank)
    seq = g.p2p_seq.get(key, 0)
    g.p2p_seq[key] = seq + 1
    raytpu.get(g.handle.p2p_send.remote(g.rank, dst_rank, seq,
                                        _as_numpy(tensor)))


def recv(src_rank: int, group_name: str = "default",
         timeout: Optional[float] = None) -> np.ndarray:
    import raytpu

    g = _group(group_name)
    key = (src_rank, g.rank)
    seq = g.p2p_seq.get(key, 0)
    g.p2p_seq[key] = seq + 1
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        ok, payload = raytpu.get(
            g.handle.p2p_recv.remote(src_rank, g.rank, seq))
        if ok:
            return payload
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(f"recv from rank {src_rank} timed out")
        time.sleep(0.002)
