"""raytpu.collective — collectives on two planes.

Host plane (orchestration-scale, numpy over the actor fabric; reference:
``ray.util.collective`` gloo backend) and device plane (compiled XLA
collectives over mesh axes; replaces the reference's NCCL backend).
"""

from raytpu.collective.collective import (
    ReduceOp,
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reducescatter,
    send,
)
from raytpu.collective import mesh_ops

__all__ = [
    "ReduceOp", "init_collective_group", "destroy_collective_group",
    "is_group_initialized", "get_rank", "get_collective_group_size",
    "allreduce", "allgather", "reducescatter", "broadcast", "barrier",
    "send", "recv", "mesh_ops",
]
