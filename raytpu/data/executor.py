"""Streaming executor — pull-based pipelined block processing.

Reference analogue: ``python/ray/data/_internal/execution/
streaming_executor.py:55`` + ``streaming_executor_state.py`` (SURVEY.md
A8): operators process blocks as distributed tasks; the driver-side loop
keeps at most ``max_in_flight`` tasks outstanding per operator
(ConcurrencyCapBackpressurePolicy analogue,
``backpressure_policy/concurrency_cap_backpressure_policy.py:18``) and
yields output blocks as they complete, preserving block order (streaming:
downstream consumption overlaps upstream production; memory is bounded by
in-flight count, not dataset size).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional

import raytpu
from raytpu.core.config import cfg
from raytpu.runtime.object_ref import ObjectRef


class ResourceBudget:
    """Object-store byte budget for one streaming execution.

    Reference analogue: ``_internal/execution/resource_manager.py`` — the
    reference bounds each execution's object-store footprint, not just
    its task count. Block sizes aren't known before a task runs, so the
    consumer feeds observed sizes back (:meth:`record_block`) and the
    admission check holds ``(in_flight + 1) * avg_block_bytes`` under the
    budget. Until the first observation the concurrency cap alone
    governs; at least one block is always admitted (no livelock).
    """

    def __init__(self, budget_bytes: Optional[int] = None):
        if not budget_bytes:
            budget_bytes = int(cfg.data_memory_budget_bytes) or int(
                0.25 * float(cfg.object_store_memory_bytes))
        self.budget_bytes = int(budget_bytes)
        self.avg_block_bytes: Optional[float] = None
        self.peak_in_flight = 0
        # Peak admissions AFTER the first size observation — the
        # steady-state footprint (cold start is governed by the
        # concurrency cap alone, so peak_in_flight can reach the window).
        self.warm_peak_in_flight = 0
        self.throttle_events = 0

    def record_block(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        if self.avg_block_bytes is None:
            self.avg_block_bytes = float(nbytes)
        else:  # EMA: recent blocks dominate (sizes drift along a scan)
            self.avg_block_bytes += 0.3 * (nbytes - self.avg_block_bytes)

    def admit(self, in_flight: int) -> bool:
        if in_flight == 0 or self.avg_block_bytes is None:
            return True
        ok = (in_flight + 1) * self.avg_block_bytes <= self.budget_bytes
        if ok:
            self.warm_peak_in_flight = max(self.warm_peak_in_flight,
                                           in_flight + 1)
        else:
            self.throttle_events += 1
        return ok


class ActorPoolStrategy:
    """Run a map stage on a pool of long-lived actors instead of per-block
    tasks (reference: ``ActorPoolStrategy`` / the actor-pool MapOperator,
    ``execution/operators/map_operator.py:34``) — the TPU-relevant case:
    a stage whose setup is expensive (load model, jit-compile) amortizes
    it across every block the actor processes."""

    def __init__(self, size: int = 2):
        self.size = max(1, int(size))


class OpSpec:
    """One pipeline stage: a remote transform over blocks.

    fn(block) -> block. ``fn`` may also be a CLASS: it is instantiated
    once per pool actor (stateful UDF; requires ``compute``).
    """

    def __init__(self, name: str, fn: Callable, *, num_cpus: float = 1.0,
                 compute: "ActorPoolStrategy" = None):
        self.name = name
        self.fn = fn
        self.num_cpus = num_cpus
        self.compute = compute


def fuse_ops(ops: List[OpSpec]) -> List[OpSpec]:
    """Logical-plan optimizer rule: consecutive task-based map stages fuse
    into ONE remote task so intermediate blocks never hit the object
    store (reference: ``OperatorFusionRule``,
    ``_internal/logical/rules/operator_fusion.py``). Actor-pool stages
    are fusion barriers (different execution substrate)."""
    fused: List[OpSpec] = []
    for op in ops:
        prev = fused[-1] if fused else None
        if (prev is not None and prev.compute is None
                and op.compute is None):
            def composed(block, _f=prev.fn, _g=op.fn):
                return _g(_f(block))

            fused[-1] = OpSpec(f"{prev.name}->{op.name}", composed,
                               num_cpus=max(prev.num_cpus, op.num_cpus))
        else:
            fused.append(op)
    return fused


class _PoolStage:
    """Actor-pool execution of one stage: blocks dispatch round-robin to
    ``size`` actors, each hosting the (possibly stateful) UDF."""

    def __init__(self, op: OpSpec):
        fn = op.fn

        @raytpu.remote(num_cpus=op.num_cpus)
        class _MapWorker:
            def __init__(self):
                import inspect as _inspect

                self._fn = fn() if _inspect.isclass(fn) else fn

            def apply(self, block):
                return self._fn(block)

        # Cap the pool at what the cluster can actually schedule: actors
        # beyond capacity would never start, and blocks round-robined to
        # them would wait forever (silent pipeline deadlock).
        size = op.compute.size
        try:
            total_cpus = float(raytpu.cluster_resources().get("CPU", 1.0))
            cap = max(1, int(total_cpus // max(op.num_cpus, 1e-9)))
            size = min(size, cap)
        except Exception:
            pass
        self.actors = [_MapWorker.remote() for _ in range(size)]
        self._next = 0

    def submit(self, ref: ObjectRef) -> ObjectRef:
        actor = self.actors[self._next % len(self.actors)]
        self._next += 1
        return actor.apply.remote(ref)

    def stop(self) -> None:
        for a in self.actors:
            try:
                raytpu.kill(a)
            except Exception:
                pass


def run_pipeline(source: Iterator, ops: List[OpSpec], *,
                 max_in_flight: int = 8,
                 budget: Optional[ResourceBudget] = None
                 ) -> Iterator[ObjectRef]:
    """Stream block refs from `source` through `ops`.

    `source` yields ObjectRefs of blocks. Returns an iterator of output
    block refs in order. Each stage runs as remote tasks (fused where
    adjacent) or on an actor pool, with a concurrency cap AND (when the
    consumer feeds a :class:`ResourceBudget`) an object-store byte
    budget; stages are chained per-block (pipeline, no barrier — block i
    can be in stage 2 while block j is in stage 0).
    """
    if not ops:
        yield from source
        return

    ops = fuse_ops(ops)
    stages = []
    pools: List[_PoolStage] = []
    for op in ops:
        if op.compute is not None:
            pool = _PoolStage(op)
            pools.append(pool)
            stages.append(pool.submit)
        else:
            @raytpu.remote(num_cpus=op.num_cpus, name=f"data::{op.name}")
            def stage(block, _fn=op.fn):
                return _fn(block)

            stages.append(stage.remote)

    def chain(ref: ObjectRef) -> ObjectRef:
        for submit in stages:
            ref = submit(ref)
        return ref

    try:
        pending: List[ObjectRef] = []  # ordered
        source_iter = iter(source)
        exhausted = False
        while pending or not exhausted:
            while not exhausted and len(pending) < max_in_flight and (
                    budget is None or budget.admit(len(pending))):
                try:
                    in_ref = next(source_iter)
                except StopIteration:
                    exhausted = True
                    break
                pending.append(chain(in_ref))
                if budget is not None:
                    budget.peak_in_flight = max(budget.peak_in_flight,
                                                len(pending))
            if pending:
                # Ordered streaming: wait on the head (completion order
                # within the window doesn't matter for memory; order does
                # for output).
                head = pending.pop(0)
                raytpu.wait([head], num_returns=1)
                yield head
    finally:
        for pool in pools:
            pool.stop()


def materialize_refs(refs: Iterator[ObjectRef]) -> List[ObjectRef]:
    return list(refs)
