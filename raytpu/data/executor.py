"""Streaming executor — pull-based pipelined block processing.

Reference analogue: ``python/ray/data/_internal/execution/
streaming_executor.py:55`` + ``streaming_executor_state.py`` (SURVEY.md
A8): operators process blocks as distributed tasks; the driver-side loop
keeps at most ``max_in_flight`` tasks outstanding per operator
(ConcurrencyCapBackpressurePolicy analogue,
``backpressure_policy/concurrency_cap_backpressure_policy.py:18``) and
yields output blocks as they complete, preserving block order (streaming:
downstream consumption overlaps upstream production; memory is bounded by
in-flight count, not dataset size).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional

import raytpu
from raytpu.runtime.object_ref import ObjectRef


class OpSpec:
    """One pipeline stage: a remote transform over blocks.

    fn(block) -> block (or list of blocks for flat ops).
    """

    def __init__(self, name: str, fn: Callable, *, num_cpus: float = 1.0,
                 flat: bool = False):
        self.name = name
        self.fn = fn
        self.num_cpus = num_cpus
        self.flat = flat


def run_pipeline(source: Iterator, ops: List[OpSpec], *,
                 max_in_flight: int = 8) -> Iterator[ObjectRef]:
    """Stream block refs from `source` through `ops`.

    `source` yields ObjectRefs of blocks. Returns an iterator of output
    block refs in order. Each stage runs as remote tasks with a
    concurrency cap; stages are chained per-block (pipeline, no barrier —
    block i can be in stage 2 while block j is in stage 0).
    """
    if not ops:
        yield from source
        return

    remotes = []
    for op in ops:
        @raytpu.remote(num_cpus=op.num_cpus, name=f"data::{op.name}")
        def stage(block, _fn=op.fn):
            return _fn(block)

        remotes.append(stage)

    def chain(ref: ObjectRef) -> ObjectRef:
        for r in remotes:
            ref = r.remote(ref)
        return ref

    pending: List[ObjectRef] = []  # ordered
    source_iter = iter(source)
    exhausted = False
    while pending or not exhausted:
        while not exhausted and len(pending) < max_in_flight:
            try:
                in_ref = next(source_iter)
            except StopIteration:
                exhausted = True
                break
            pending.append(chain(in_ref))
        if pending:
            # Ordered streaming: wait on the head (completion order within
            # the window doesn't matter for memory; order does for output).
            head = pending.pop(0)
            raytpu.wait([head], num_returns=1)
            yield head


def materialize_refs(refs: Iterator[ObjectRef]) -> List[ObjectRef]:
    return list(refs)
