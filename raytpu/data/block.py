"""Blocks — the unit of data movement.

Reference analogue: Ray Data blocks (Arrow tables in plasma; accessor in
``python/ray/data/_internal/block_accessor``-land). Here a block is a
pyarrow Table (structured data) or a dict of numpy arrays (tensor data) —
both zero-copy friendly through the shm object store (numpy buffers ride
as raw buffers; arrow via its own buffer protocol).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Union

import numpy as np

Block = Union["pyarrow.Table", Dict[str, np.ndarray]]  # noqa: F821


class BlockAccessor:
    """Uniform view over the two block kinds."""

    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    def num_rows(self) -> int:
        import pyarrow as pa

        if isinstance(self.block, pa.Table):
            return self.block.num_rows
        if not self.block:
            return 0
        return len(next(iter(self.block.values())))

    def to_arrow(self):
        import pyarrow as pa

        if isinstance(self.block, pa.Table):
            return self.block
        return pa.table({k: pa.array(np.asarray(v))
                         for k, v in self.block.items()})

    def to_numpy(self) -> Dict[str, np.ndarray]:
        import pyarrow as pa

        if isinstance(self.block, pa.Table):
            return {name: col.to_numpy(zero_copy_only=False)
                    for name, col in zip(self.block.column_names,
                                         self.block.columns)}
        return self.block

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def to_rows(self) -> List[dict]:
        npd = self.to_numpy()
        keys = list(npd.keys())
        n = self.num_rows()
        return [{k: npd[k][i] for k in keys} for i in range(n)]

    def slice(self, start: int, end: int) -> Block:
        import pyarrow as pa

        if isinstance(self.block, pa.Table):
            return self.block.slice(start, end - start)
        return {k: v[start:end] for k, v in self.block.items()}

    def size_bytes(self) -> int:
        import pyarrow as pa

        if isinstance(self.block, pa.Table):
            return self.block.nbytes
        return sum(np.asarray(v).nbytes for v in self.block.values())

    def schema(self):
        import pyarrow as pa

        if isinstance(self.block, pa.Table):
            return self.block.schema
        return {k: np.asarray(v).dtype for k, v in self.block.items()}


def block_from_rows(rows: List[Any]) -> Block:
    """List of dicts (or scalars → {'item': ...}) to a block."""
    import pyarrow as pa

    if not rows:
        return pa.table({})
    if isinstance(rows[0], dict):
        keys = rows[0].keys()
        return pa.table({k: [r[k] for r in rows] for k in keys})
    return pa.table({"item": list(rows)})


def concat_blocks(blocks: List[Block]) -> Block:
    import pyarrow as pa

    # Empty blocks (an empty file/shard read) carry no schema — mixing
    # one in must not drop the real rows or fail the arrow concat.
    blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0]
    if not blocks:
        return pa.table({})
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([np.asarray(b[k]) for b in blocks])
                for k in keys}
    # promote: blocks from different files may have different column
    # sets (e.g. webdataset shards with differing extensions) — absent
    # columns fill with nulls instead of ArrowInvalid.
    return pa.concat_tables(
        [BlockAccessor(b).to_arrow() for b in blocks],
        promote_options="default")


def batch_format_view(block: Block, batch_format: str):
    acc = BlockAccessor(block)
    if batch_format in ("numpy", "default"):
        return acc.to_numpy()
    if batch_format == "pandas":
        return acc.to_pandas()
    if batch_format in ("pyarrow", "arrow"):
        return acc.to_arrow()
    raise ValueError(f"unknown batch_format {batch_format!r}")


def normalize_batch_output(out: Any) -> Block:
    """Accept what user map_batches fns return: dict of arrays, arrow
    table, pandas frame, or list of rows."""
    import pyarrow as pa

    if isinstance(out, pa.Table):
        return out
    if isinstance(out, dict):
        return {k: np.asarray(v) for k, v in out.items()}
    try:
        import pandas as pd

        if isinstance(out, pd.DataFrame):
            return pa.Table.from_pandas(out, preserve_index=False)
    except ImportError:
        pass
    if isinstance(out, list):
        return block_from_rows(out)
    raise TypeError(
        f"map_batches function returned {type(out)}; expected dict of "
        "arrays, pyarrow.Table, pandas.DataFrame, or list of rows")
