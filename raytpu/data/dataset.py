"""Dataset — lazy, streaming, distributed datasets.

Reference analogue: ``python/ray/data/dataset.py:137`` (Dataset),
``read_api.py``, logical plan + streaming execution (SURVEY.md §2.3, A8).
A Dataset is a lazy plan: a block source plus a chain of operators;
consumption streams blocks through remote tasks with bounded in-flight
work (:mod:`raytpu.data.executor`). Blocks live in the object store; the
driver holds refs only.

Global ops (sort/repartition/random_shuffle) run as distributed two-phase
exchanges (map partition tasks + reduce merge tasks) — the driver holds
refs only, so dataset size is bounded by the cluster object store, not
driver RAM. Everything else streams.
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import raytpu
from raytpu.data.block import (
    BlockAccessor,
    batch_format_view,
    block_from_rows,
    concat_blocks,
    normalize_batch_output,
)
from raytpu.data.executor import OpSpec, run_pipeline


class Dataset:
    def __init__(self, source_fn: Callable[[], Iterator], ops: List[OpSpec],
                 name: str = "dataset"):
        self._source_fn = source_fn  # () -> iterator of block refs
        self._ops = ops
        self._name = name

    # -- transforms (lazy) ----------------------------------------------------

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    num_cpus: float = 1.0, batch_size: Optional[int] = None,
                    fn_kwargs: Optional[dict] = None,
                    compute=None) -> "Dataset":
        """Apply fn to whole blocks (reference: ``Dataset.map_batches``).
        `batch_size=None` keeps source block boundaries (fastest).

        ``compute=ActorPoolStrategy(size=n)`` runs the stage on n
        long-lived actors; ``fn`` may then be a CLASS whose instances are
        built once per actor (stateful UDF — the place to load/jit a
        model once and reuse it per block)."""
        import inspect as _inspect

        kw = fn_kwargs or {}

        if _inspect.isclass(fn):
            if compute is None:
                raise ValueError(
                    "class-based map_batches UDFs require "
                    "compute=ActorPoolStrategy(...)")
            user_cls = fn

            class op:  # instantiated once per pool actor
                def __init__(self):
                    self._inner = user_cls()

                def __call__(self, block):
                    view = batch_format_view(block, batch_format)
                    return normalize_batch_output(self._inner(view, **kw))
        else:
            def op(block):
                view = batch_format_view(block, batch_format)
                return normalize_batch_output(fn(view, **kw))

        ds = self._with_op(OpSpec(getattr(fn, "__name__", "map_batches"),
                                  op, num_cpus=num_cpus, compute=compute))
        if batch_size is not None:
            ds = ds._rechunk(batch_size)
        return ds

    def map(self, fn: Callable, *, num_cpus: float = 1.0) -> "Dataset":
        def op(block):
            rows = BlockAccessor(block).to_rows()
            return block_from_rows([fn(r) for r in rows])

        return self._with_op(OpSpec(getattr(fn, "__name__", "map"), op,
                                    num_cpus=num_cpus))

    def filter(self, fn: Callable) -> "Dataset":
        def op(block):
            rows = BlockAccessor(block).to_rows()
            return block_from_rows([r for r in rows if fn(r)])

        return self._with_op(OpSpec("filter", op))

    def flat_map(self, fn: Callable) -> "Dataset":
        def op(block):
            rows = BlockAccessor(block).to_rows()
            out = []
            for r in rows:
                out.extend(fn(r))
            return block_from_rows(out)

        return self._with_op(OpSpec("flat_map", op))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def op(batch):
            batch = dict(batch)
            batch[name] = fn(batch)
            return batch

        return self.map_batches(op, batch_format="numpy")

    def drop_columns(self, cols: Sequence[str]) -> "Dataset":
        def op(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return self.map_batches(op, batch_format="numpy")

    def select_columns(self, cols: Sequence[str]) -> "Dataset":
        def op(batch):
            return {k: batch[k] for k in cols}

        return self.map_batches(op, batch_format="numpy")

    def limit(self, n: int) -> "Dataset":
        parent = self

        def source():
            remaining = n
            for ref in parent._iter_block_refs():
                if remaining <= 0:
                    break
                block = raytpu.get(ref)
                rows = BlockAccessor(block).num_rows()
                if rows <= remaining:
                    remaining -= rows
                    yield ref
                else:
                    yield raytpu.put(
                        BlockAccessor(block).slice(0, remaining))
                    remaining = 0

        return Dataset(source, [], name=f"{self._name}.limit({n})")

    def union(self, *others: "Dataset") -> "Dataset":
        parents = [self, *others]

        def source():
            for p in parents:
                yield from p._iter_block_refs()

        return Dataset(source, [], name="union")

    @staticmethod
    def _global_offsets(in_refs) -> np.ndarray:
        """Per-block global row offsets (len+1, int64) via one remote
        count pass — shared by the offset-based exchanges."""

        @raytpu.remote(name="data::count")
        def count(block):
            return BlockAccessor(block).num_rows()

        counts = raytpu.get([count.remote(r) for r in in_refs])
        return np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)

    def _all_to_all(self, num_out: Optional[int], assign_fn, name: str,
                    post_fn=None, prepare_fn=None) -> "Dataset":
        """Two-phase distributed shuffle (reference:
        ``python/ray/data/_internal/planner/exchange/`` push-based
        shuffle): map tasks partition each input block into ``n_out``
        pieces (``assign_fn(block_numpy, rows, block_idx, n_out, aux) ->
        partition id per row``); reduce tasks concatenate piece j of every
        map output (+ optional ``post_fn`` e.g. a local sort). The driver
        only ever holds refs — dataset size is bounded by the cluster's
        object store, not driver RAM. ``num_out=None`` preserves the input
        block count (parallelism follows the data); ``prepare_fn(in_refs,
        n_out)`` computes small driver-side aux state (offsets, sort
        boundaries) before the exchange."""
        parent = self

        def source():
            in_refs = list(parent._iter_block_refs())
            if not in_refs:
                return
            n_out = max(1, int(num_out) if num_out else len(in_refs))
            aux = prepare_fn(in_refs, n_out) if prepare_fn else None

            @raytpu.remote(num_returns=n_out, name=f"data::{name}-map")
            def split(block, idx):
                npd = BlockAccessor(block).to_numpy()
                rows = BlockAccessor(block).num_rows()
                assign = assign_fn(npd, rows, idx, n_out, aux)
                pieces = []
                for j in range(n_out):
                    mask = assign == j
                    pieces.append({k: np.asarray(v)[mask]
                                   for k, v in npd.items()})
                return tuple(pieces) if n_out > 1 else pieces[0]

            @raytpu.remote(name=f"data::{name}-reduce")
            def merge(j, *pieces):
                live = [p for p in pieces
                        if BlockAccessor(p).num_rows() > 0]
                out = concat_blocks(live) if live else pieces[0]
                if post_fn is not None:
                    out = post_fn(out, j)
                return out

            parts = [split.remote(ref, i) for i, ref in enumerate(in_refs)]
            if n_out == 1:
                parts = [[p] for p in parts]
            for j in range(n_out):
                yield merge.remote(j, *[p[j] for p in parts])

        return Dataset(source, [], name=f"{self._name}.{name}")

    def repartition(self, num_blocks: int) -> "Dataset":
        """Distributed all-to-all repartition into near-equal blocks,
        PRESERVING row order (reference: ``Dataset.repartition``): a cheap
        remote count pass gives global offsets, rows then map to
        contiguous output ranges."""

        def prepare(in_refs, n_out):
            offsets = self._global_offsets(in_refs)
            total = int(offsets[-1])
            per = max(1, -(-total // n_out))
            return offsets, per

        def assign(npd, rows, idx, n_out, aux):
            offsets, per = aux
            return np.minimum(
                (int(offsets[idx]) + np.arange(rows)) // per, n_out - 1)

        return self._all_to_all(num_blocks, assign, "repartition",
                                prepare_fn=prepare)

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        """Distributed random shuffle: rows hash to random reducers, each
        reducer permutes locally — a true all-to-all, no driver
        materialization (reference: ``Dataset.random_shuffle``). Output
        parallelism follows the input block count unless overridden."""

        def assign(npd, rows, idx, n_out, aux):
            rng = np.random.default_rng(
                None if seed is None else seed + 7919 * idx)
            return rng.integers(0, n_out, size=rows)

        def post(block, j):
            npd = BlockAccessor(block).to_numpy()
            n = BlockAccessor(block).num_rows()
            rng = np.random.default_rng(
                None if seed is None else seed + 104729 * (j + 1))
            perm = rng.permutation(n)
            return {k: np.asarray(v)[perm] for k, v in npd.items()}

        return self._all_to_all(num_blocks, assign, "shuffle",
                                post_fn=post)

    def sort(self, key: str, descending: bool = False,
             num_blocks: Optional[int] = None) -> "Dataset":
        """Distributed sample sort: sample boundaries from every block,
        range-partition rows to reducers, reducers sort locally — output
        blocks are globally ordered (reference: ``Dataset.sort`` over the
        sort exchange). Sampling pulls only small per-block samples to the
        driver, never the data."""

        def prepare(in_refs, n_out):
            @raytpu.remote(name="data::sort-sample")
            def sample(block):
                vals = np.asarray(BlockAccessor(block).to_numpy()[key])
                if vals.size == 0:
                    return vals
                k = min(64, vals.size)
                idx = np.linspace(0, vals.size - 1, k).astype(np.int64)
                return np.sort(vals)[idx]

            samples = np.concatenate(
                [s for s in raytpu.get([sample.remote(r)
                                        for r in in_refs])
                 if np.asarray(s).size] or [np.zeros(0)])
            if samples.size == 0:
                return np.zeros(0)
            qs = np.linspace(0, 1, n_out + 1)[1:-1]
            return np.quantile(np.sort(samples), qs)

        def assign(npd, rows, idx, n_out, boundaries):
            vals = np.asarray(npd[key])
            part = np.searchsorted(boundaries, vals, side="right")
            if descending:
                part = (n_out - 1) - part
            return part

        def post(block, j):
            npd = BlockAccessor(block).to_numpy()
            order = np.argsort(np.asarray(npd[key]), kind="stable")
            if descending:
                order = order[::-1]
            return {k2: np.asarray(v)[order] for k2, v in npd.items()}

        return self._all_to_all(num_blocks, assign, "sort",
                                post_fn=post, prepare_fn=prepare)

    def random_sample(self, fraction: float, *,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: ``Dataset.random_sample``).
        The seed is salted per block (like random_shuffle) — one shared
        seed would draw the SAME mask in every block, correlating the
        sample across the dataset."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")
        parent = self

        def source():
            @raytpu.remote(name="data::sample")
            def sample(block, idx):
                rng = np.random.default_rng(
                    None if seed is None else seed + 7919 * idx)
                npd = BlockAccessor(block).to_numpy()
                n = BlockAccessor(block).num_rows()
                mask = rng.random(n) < fraction
                return {k: np.asarray(v)[mask] for k, v in npd.items()}

            for i, ref in enumerate(parent._iter_block_refs()):
                yield sample.remote(ref, i)

        return Dataset(source, [], name=f"{self._name}.sample")

    def unique(self, column: str) -> List[Any]:
        """Distinct values of one column (reference: ``Dataset.unique``):
        per-block distincts in remote tasks, merged on the driver —
        result size is the number of DISTINCT values, not rows."""

        @raytpu.remote(name="data::unique")
        def distinct(block):
            return np.unique(np.asarray(
                BlockAccessor(block).to_numpy()[column]))

        refs = [distinct.remote(r) for r in self._iter_block_refs()]
        out: set = set()
        for vals in raytpu.get(refs):
            out.update(vals.tolist())
        return sorted(out)

    def split_at_indices(self, indices: Sequence[int]) -> List["Dataset"]:
        """Split by global row offsets (reference:
        ``Dataset.split_at_indices``): ``[3, 7]`` -> rows [0,3), [3,7),
        [7,end) — order preserved, distributed via the offset exchange."""
        indices = sorted(int(i) for i in indices)
        if any(i < 0 for i in indices):
            raise ValueError("indices must be non-negative")
        n_out = len(indices) + 1

        def prepare(in_refs, n):
            return (self._global_offsets(in_refs),
                    np.asarray(indices, np.int64))

        def assign(npd, rows, idx, n, aux):
            offsets, bounds = aux
            global_rows = int(offsets[idx]) + np.arange(rows)
            return np.searchsorted(bounds, global_rows, side="right")

        parts = self._all_to_all(n_out, assign, "split_at_indices",
                                 prepare_fn=prepare)
        refs = list(parts._iter_block_refs())
        if not refs:  # empty upstream: still n_out (empty) datasets
            return [Dataset(lambda: iter(()), [],
                            name=f"{self._name}.split_at")
                    for _ in range(n_out)]
        return [Dataset(lambda r=ref: iter([r]), [],
                        name=f"{self._name}.split_at")
                for ref in refs]

    def take_batch(self, batch_size: int = 20,
                   batch_format: str = "numpy"):
        """First ``batch_size`` rows as one batch (reference:
        ``Dataset.take_batch`` — raises on an empty dataset)."""
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format):
            return batch
        raise ValueError(f"dataset {self._name!r} is empty")

    def groupby(self, key: str) -> "GroupedData":
        """Distributed group-by (reference: ``Dataset.groupby`` →
        ``GroupedData``): rows hash-partition to reducers on a
        deterministic key hash (every group lands whole on one reducer),
        aggregations/`map_groups` then run per-reducer with no driver
        materialization."""
        return GroupedData(self, key)

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned column zip (reference: ``Dataset.zip``): both sides
        are repartitioned to identical row offsets, then blocks merge
        columnwise in remote tasks. Column collisions raise."""
        left, right = self, other

        def source():
            n_l, n_r = left.count(), right.count()
            if n_l != n_r:
                raise ValueError(
                    f"zip requires equal row counts, got {n_l} vs {n_r}")
            blocks = max(1, -(-n_l // 4096))
            l_refs = list(left.repartition(blocks)._iter_block_refs())
            r_refs = list(right.repartition(blocks)._iter_block_refs())

            @raytpu.remote(name="data::zip")
            def merge(a, b):
                na = BlockAccessor(a).to_numpy()
                nb = BlockAccessor(b).to_numpy()
                clash = set(na) & set(nb)
                if clash:
                    raise ValueError(f"zip column collision: {sorted(clash)}")
                return {**na, **nb}

            for a, b in zip(l_refs, r_refs):
                yield merge.remote(a, b)

        return Dataset(source, [], name=f"{self._name}.zip")

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Split into ``n`` disjoint datasets (reference: ``Dataset.split``).
        ``equal=True`` repartitions first so row counts match to within
        one block."""
        src = self.repartition(n) if equal else self
        refs = list(src._iter_block_refs())
        shards: List[List] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)

        def make(shard):
            return Dataset(lambda s=tuple(shard): iter(s), [],
                           name=f"{self._name}.split")

        return [make(s) for s in shards]

    def train_test_split(self, test_size: float, *,
                         shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> Tuple["Dataset", "Dataset"]:
        """(train, test) row split by global offset (reference:
        ``Dataset.train_test_split``)."""
        if not 0.0 < test_size < 1.0:
            raise ValueError("test_size must be in (0, 1)")
        ds = self.random_shuffle(seed=seed) if shuffle else self

        def prepare(in_refs, n_out):
            offsets = self._global_offsets(in_refs)
            boundary = int(round(offsets[-1] * (1.0 - test_size)))
            return offsets, boundary

        def assign(npd, rows, idx, n_out, aux):
            offsets, boundary = aux
            return ((int(offsets[idx]) + np.arange(rows)) >= boundary
                    ).astype(np.int64)

        both = ds._all_to_all(2, assign, "train_test_split",
                              prepare_fn=prepare)
        refs = list(both._iter_block_refs())
        if not refs:  # empty upstream: two empty datasets, like split_at
            return (Dataset(lambda: iter(()), [],
                            name=f"{self._name}.train"),
                    Dataset(lambda: iter(()), [],
                            name=f"{self._name}.test"))
        train_ref, test_ref = refs[0], refs[1]
        return (Dataset(lambda r=train_ref: iter([r]), [],
                        name=f"{self._name}.train"),
                Dataset(lambda r=test_ref: iter([r]), [],
                        name=f"{self._name}.test"))

    # -- consumption ----------------------------------------------------------

    def _iter_block_refs(self) -> Iterator:
        return run_pipeline(self._source_fn(), self._ops)

    def iter_blocks(self) -> Iterator:
        # The consuming loop observes real block sizes and feeds them to
        # the executor's byte budget (reference: ResourceManager — memory
        # backpressure, not just a concurrency cap).
        from raytpu.data.executor import ResourceBudget

        budget = ResourceBudget()
        self._last_budget = budget  # introspection/tests
        for ref in run_pipeline(self._source_fn(), self._ops,
                                budget=budget):
            block = raytpu.get(ref)
            try:
                budget.record_block(BlockAccessor(block).size_bytes())
            except Exception:
                pass
            yield block

    def iter_rows(self) -> Iterator[dict]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator:
        """Re-chunk the block stream into fixed-size batches."""
        carry: List = []
        carry_rows = 0
        for block in self.iter_blocks():
            carry.append(block)
            carry_rows += BlockAccessor(block).num_rows()
            while carry_rows >= batch_size:
                whole = concat_blocks(carry)
                acc = BlockAccessor(whole)
                yield batch_format_view(acc.slice(0, batch_size),
                                        batch_format)
                rest = acc.slice(batch_size, acc.num_rows())
                carry = [rest]
                carry_rows = BlockAccessor(rest).num_rows()
        if carry_rows and not drop_last:
            whole = concat_blocks(carry)
            yield batch_format_view(whole, batch_format)

    def iter_jax_batches(self, *, batch_size: int = 256,
                         drop_last: bool = False, device=None,
                         sharding=None) -> Iterator:
        """Batches as jax arrays on-device (the TPU-first analogue of the
        reference's ``iter_torch_batches``): numpy batches are device_put
        onto ``device``/``sharding`` (default: the default device), so the
        training loop consumes ready device buffers."""
        import jax
        import jax.numpy as jnp

        target = sharding if sharding is not None else device
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if target is not None:
                # Straight host->target transfer: jnp.asarray first would
                # commit the full array to device 0 and re-shard — double
                # traffic, and a device-0 hotspot under a sharding.
                yield jax.device_put(batch, target)
            else:
                yield {k: jnp.asarray(v) for k, v in batch.items()}

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False, device=None,
                           dtypes=None) -> Iterator:
        """Batches as torch tensors (reference: ``iter_torch_batches``).
        Migration aid: existing torch training loops consume this
        unchanged; new TPU code should prefer :meth:`iter_jax_batches`.
        ``dtypes``: a torch dtype (all columns) or {column: dtype}."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                t = torch.from_numpy(np.ascontiguousarray(v))
                want = (dtypes.get(k) if isinstance(dtypes, dict)
                        else dtypes)
                if want is not None or device is not None:
                    # single .to(): one copy, not one per conversion
                    t = t.to(device=device, dtype=want)
                out[k] = t
            yield out

    def take(self, n: int = 20) -> List[dict]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[dict]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        return sum(BlockAccessor(b).num_rows() for b in self.iter_blocks())

    def sum(self, col: str):
        return sum(float(np.asarray(BlockAccessor(b).to_numpy()[col]).sum())
                   for b in self.iter_blocks())

    def mean(self, col: str):
        total, n = 0.0, 0
        for b in self.iter_blocks():
            arr = np.asarray(BlockAccessor(b).to_numpy()[col])
            total += float(arr.sum())
            n += arr.size
        return total / max(n, 1)

    def min(self, col: str):
        # Skip zero-row blocks (exchanges can produce them).
        return min(float(np.asarray(arr).min()) for arr in (
            BlockAccessor(b).to_numpy()[col] for b in self.iter_blocks())
            if np.asarray(arr).size)

    def max(self, col: str):
        return max(float(np.asarray(arr).max()) for arr in (
            BlockAccessor(b).to_numpy()[col] for b in self.iter_blocks())
            if np.asarray(arr).size)

    def schema(self):
        for block in self.iter_blocks():
            return BlockAccessor(block).schema()
        return None

    def to_pandas(self):
        import pandas as pd

        frames = [BlockAccessor(b).to_pandas() for b in self.iter_blocks()]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def materialize(self) -> "Dataset":
        refs = list(self._iter_block_refs())

        def source():
            yield from refs

        return Dataset(source, [], name=f"{self._name}.materialized")

    def stats(self) -> dict:
        blocks = 0
        rows = 0
        nbytes = 0
        for b in self.iter_blocks():
            acc = BlockAccessor(b)
            blocks += 1
            rows += acc.num_rows()
            nbytes += acc.size_bytes()
        return {"blocks": blocks, "rows": rows, "bytes": nbytes}

    # -- train ingest ---------------------------------------------------------

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None) -> List["DataIterator"]:
        """n coordinated iterators over one pass of the stream (reference:
        ``Dataset.streaming_split``, ``dataset.py:1141`` — powered by a
        coordinator actor + OutputSplitter)."""
        coordinator = _SplitCoordinator.options(name=None).remote(
            self, n)
        return [DataIterator(coordinator, i) for i in range(n)]

    # -- writes ---------------------------------------------------------------

    def write_parquet(self, path: str, *,
                      partition_cols: Optional[Sequence[str]] = None
                      ) -> None:
        """Parquet sink; with ``partition_cols``, hive-style layout —
        ``path/col=value/.../part-N.parquet`` with the partition columns
        dropped from the files (reference: ``Dataset.write_parquet``
        partitioning; readable back via ``read_parquet`` which
        re-attaches them from the path)."""
        import os
        import urllib.parse

        import pyarrow.compute as pc
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        if not partition_cols:
            for i, block in enumerate(self.iter_blocks()):
                pq.write_table(BlockAccessor(block).to_arrow(),
                               f"{path}/part-{i:05d}.parquet")
            return
        import math

        from raytpu.data.read_api import HIVE_NULL

        _nan = object()  # NaN can't key a set (nan != nan): normalize

        def norm(v):
            return _nan if isinstance(v, float) and math.isnan(v) else v

        cols = list(partition_cols)
        for i, block in enumerate(self.iter_blocks()):
            table = BlockAccessor(block).to_arrow()
            missing = [c for c in cols if c not in table.column_names]
            if missing:
                raise KeyError(f"partition_cols {missing} not in "
                               f"columns {table.column_names}")
            combos = {tuple(norm(row[c]) for c in cols)
                      for row in table.select(cols).to_pylist()}
            for combo in sorted(combos, key=repr):
                mask = None
                for c, v in zip(cols, combo):
                    m = (pc.is_null(table[c]) if v is None
                         else pc.is_nan(table[c]) if v is _nan
                         else pc.equal(table[c], v))
                    mask = m if mask is None else pc.and_(mask, m)
                sub = table.filter(mask).drop_columns(cols)
                segs = "/".join(
                    f"{c}=" + (HIVE_NULL if v is None else "nan"
                               if v is _nan else
                               urllib.parse.quote(str(v), safe=""))
                    for c, v in zip(cols, combo))
                os.makedirs(f"{path}/{segs}", exist_ok=True)
                pq.write_table(sub,
                               f"{path}/{segs}/part-{i:05d}.parquet")

    def write_orc(self, path: str) -> None:
        """ORC sink, one file per block (reference analogue:
        ``Dataset.write_orc``; pyarrow.orc codec)."""
        import os

        from pyarrow import orc

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            orc.write_table(BlockAccessor(block).to_arrow(),
                            f"{path}/part-{i:05d}.orc")

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            BlockAccessor(block).to_pandas().to_csv(
                f"{path}/part-{i:05d}.csv", index=False)

    def write_json(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            BlockAccessor(block).to_pandas().to_json(
                f"{path}/part-{i:05d}.json", orient="records", lines=True)

    def write_numpy(self, path: str, column: str) -> None:
        """One ``.npy`` per block of ``column`` (reference:
        ``Dataset.write_numpy``)."""
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            arr = BlockAccessor(block).to_numpy()[column]
            np.save(f"{path}/part-{i:05d}.npy", arr)

    def write_tfrecords(self, path: str) -> None:
        """One ``.tfrecord`` shard per block, rows encoded as
        ``tf.train.Example`` protos (reference:
        ``Dataset.write_tfrecords``; codec in
        :mod:`raytpu.data.tfrecord` — interoperable with TensorFlow's
        TFRecordWriter framing)."""
        import os

        from raytpu.data.tfrecord import encode_example, write_records

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            rows = BlockAccessor(block).to_rows()
            write_records(
                f"{path}/part-{i:05d}.tfrecord",
                [encode_example(r) for r in rows])

    def write_avro(self, path: str, *, schema: Optional[dict] = None,
                   codec: str = "null") -> None:
        """One ``.avro`` object container file per block (reference:
        ``Dataset.write_avro``; dependency-free OCF codec in
        :mod:`raytpu.data.avro`). The record schema is inferred from the
        rows unless given; ``codec``: ``null`` or ``deflate``."""
        import os

        from raytpu.data.avro import infer_schema, write_file

        os.makedirs(path, exist_ok=True)
        # One schema for the whole dataset (external directory readers
        # expect consistent part schemas): inferred over ALL rows when
        # not given, so a column that is null-free in one block but
        # nullable in another still unifies.
        parts: List[List[dict]] = []
        for block in self.iter_blocks():
            parts.append([_plain_row(r)
                          for r in BlockAccessor(block).to_rows()])
        sch = schema or infer_schema([r for rows in parts for r in rows])
        for i, rows in enumerate(parts):
            write_file(f"{path}/part-{i:05d}.avro", sch, rows,
                       codec=codec)

    def write_sql(self, sql: str, connection_factory: Callable) -> None:
        """Write rows through a DB-API connection (reference:
        ``Dataset.write_sql`` / ``sql_datasink.py`` — ``sql`` is the
        parameterized INSERT, e.g. ``INSERT INTO t VALUES (?, ?)``;
        rows go in ``executemany`` batches so one bad row can't grow an
        unbounded buffer)."""
        MAX_ROWS_PER_WRITE = 128
        conn = connection_factory()
        try:
            cursor = conn.cursor()
            # Bind by the FIRST row's key order, not each dict's insertion
            # order — blocks produced by different tasks may carry the
            # same columns in different order, which would silently write
            # values into the wrong columns.
            keys: Optional[List[str]] = None
            for block in self.iter_blocks():
                values = []
                for row in BlockAccessor(block).to_rows():
                    plain = _plain_row(row)
                    if keys is None:
                        keys = list(plain)
                    elif set(plain) != set(keys):
                        raise ValueError(
                            f"write_sql: row columns {sorted(plain)} do not "
                            f"match first row's columns {sorted(keys)}")
                    values.append(tuple(plain[k] for k in keys))
                    if len(values) == MAX_ROWS_PER_WRITE:
                        cursor.executemany(sql, values)
                        values = []
                if values:
                    cursor.executemany(sql, values)
            conn.commit()
        finally:
            conn.close()

    def write_images(self, path: str, column: str, *,
                     file_format: str = "png",
                     filename_column: Optional[str] = None) -> None:
        """One image file per row from an array column (reference:
        ``Dataset.write_images`` / ``image_datasink.py``). Filenames
        come from ``filename_column`` when given, else sequential;
        uint8 HxWxC (or HxW grayscale) arrays are expected — readable
        back via ``read_images``."""
        import os

        from PIL import Image

        os.makedirs(path, exist_ok=True)
        n = 0
        for block in self.iter_blocks():
            for row in BlockAccessor(block).to_rows():
                arr = np.asarray(row[column])
                if arr.dtype != np.uint8:
                    # read_images yields float32 0-255; PIL wants uint8.
                    arr = np.clip(arr, 0, 255).astype(np.uint8)
                if filename_column:
                    # Extension-less names give PIL nothing to infer the
                    # format from; pass it explicitly ("jpg" is the PIL
                    # format "JPEG").
                    name = str(row[filename_column])
                    fmt = {"jpg": "JPEG"}.get(file_format.lower(),
                                              file_format.upper())
                    Image.fromarray(arr).save(os.path.join(path, name),
                                              format=fmt)
                else:
                    name = f"{n:06d}.{file_format}"
                    Image.fromarray(arr).save(os.path.join(path, name))
                n += 1

    def write_webdataset(self, path: str) -> None:
        """One ``.tar`` shard per block in WebDataset layout (reference:
        ``Dataset.write_webdataset`` / ``webdataset_datasink.py``):
        each row becomes ``{__key__}.{ext}`` members, one per non-key
        column; str values encode utf-8, bytes pass through, everything
        else serializes as its ``str()``. Round-trips through
        ``read_webdataset``."""
        import io
        import os
        import tarfile

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            with tarfile.open(f"{path}/part-{i:05d}.tar", "w") as tf:
                for j, row in enumerate(BlockAccessor(block).to_rows()):
                    row = _plain_row(row)
                    key = str(row.pop("__key__", f"{i:05d}{j:05d}"))
                    for ext, value in row.items():
                        if value is None:
                            continue
                        data = (value if isinstance(value, bytes)
                                else str(value).encode("utf-8"))
                        info = tarfile.TarInfo(f"{key}.{ext}")
                        info.size = len(data)
                        tf.addfile(info, io.BytesIO(data))

    # -- internals ------------------------------------------------------------

    def _with_op(self, op: OpSpec) -> "Dataset":
        return Dataset(self._source_fn, [*self._ops, op], name=self._name)

    def _rechunk(self, rows_per_block: int) -> "Dataset":
        parent = self

        def source():
            for batch in parent.iter_batches(batch_size=rows_per_block):
                yield raytpu.put(batch)

        return Dataset(source, [], name=f"{self._name}.rechunk")

    def __repr__(self):
        ops = " -> ".join(op.name for op in self._ops) or "source"
        return f"Dataset({self._name}: {ops})"


def _plain_row(row: dict) -> dict:
    """Numpy scalars -> native Python values (avro/json writers need
    plain types; ndarray cells become lists)."""
    out = {}
    for k, v in row.items():
        if isinstance(v, np.generic):
            v = v.item()
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        out[k] = v
    return out


@raytpu.remote(num_cpus=0)
class _SplitCoordinator:
    """Feeds n consumers from one pass (OutputSplitter analogue). Blocks
    are handed out round-robin; `equal=True` semantics approximated by
    per-consumer demand-driven pull."""

    def __init__(self, dataset: Dataset, n: int):
        self.iter = dataset._iter_block_refs()
        self.n = n
        self.buffers: List[List] = [[] for _ in range(n)]
        self.exhausted = False
        self.rr = 0

    def next_ref(self, split: int):
        """Next block ref for consumer `split`, or None at end of stream."""
        while not self.buffers[split] and not self.exhausted:
            try:
                ref = next(self.iter)
            except StopIteration:
                self.exhausted = True
                break
            self.buffers[self.rr].append(ref)
            self.rr = (self.rr + 1) % self.n
        if self.buffers[split]:
            return self.buffers[split].pop(0)
        return None


class DataIterator:
    """Per-worker streaming iterator (reference: ``DataIterator`` from
    ``streaming_split``; consumed in train loops via
    ``session.get_dataset_shard``)."""

    def __init__(self, coordinator, split: int):
        self._coordinator = coordinator
        self._split = split

    def iter_blocks(self):
        while True:
            # get() resolves the returned block ref one level, so this
            # yields the block value directly.
            block = raytpu.get(
                self._coordinator.next_ref.remote(self._split))
            if block is None:
                return
            yield block

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False):
        carry: List = []
        carry_rows = 0
        for block in self.iter_blocks():
            carry.append(block)
            carry_rows += BlockAccessor(block).num_rows()
            while carry_rows >= batch_size:
                whole = concat_blocks(carry)
                acc = BlockAccessor(whole)
                yield batch_format_view(acc.slice(0, batch_size),
                                        batch_format)
                rest = acc.slice(batch_size, acc.num_rows())
                carry = [rest]
                carry_rows = BlockAccessor(rest).num_rows()
        if carry_rows and not drop_last:
            yield batch_format_view(concat_blocks(carry), batch_format)

    def iter_rows(self):
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()


def _stable_hash(vals: np.ndarray) -> np.ndarray:
    """Deterministic per-row hash for exchange partitioning. Python's
    ``hash()`` is process-salted for str (PYTHONHASHSEED), which would
    scatter one group across reducers in different worker processes."""
    import zlib

    vals = np.asarray(vals)
    if vals.dtype.kind in "iub":
        v = vals.astype(np.uint64)
        v = (v ^ (v >> np.uint64(33))) * np.uint64(0xff51afd7ed558ccd)
        # Mask to a positive int64 range (2**62 - 1, NOT a single bit).
        return (v ^ (v >> np.uint64(33))).astype(np.int64) \
            & np.int64(2 ** 62 - 1)
    if vals.dtype.kind == "f":
        return _stable_hash(vals.view(np.uint64)
                            if vals.dtype == np.float64
                            else vals.astype(np.float64).view(np.uint64))
    return np.array([zlib.crc32(str(x).encode()) for x in vals],
                    dtype=np.int64)


class GroupedData:
    """Distributed group-by surface (reference: ``GroupedData`` in
    ``python/ray/data/grouped_data.py``): a hash exchange lands every
    group whole on one reducer; aggregations and ``map_groups`` run
    reducer-local."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _exchange(self, post, name: str) -> Dataset:
        key = self._key

        def assign(npd, rows, idx, n_out, aux):
            return _stable_hash(npd[key]) % n_out

        return self._ds._all_to_all(None, assign, name, post_fn=post)

    def map_groups(self, fn: Callable[[Dict[str, np.ndarray]], Any]
                   ) -> Dataset:
        """Apply ``fn(group_numpy_batch) -> batch`` per group."""
        key = self._key

        def post(block, j):
            npd = BlockAccessor(block).to_numpy()
            vals = np.asarray(npd[key])
            outs = []
            for g in np.unique(vals):
                mask = vals == g
                group = {k: np.asarray(v)[mask] for k, v in npd.items()}
                outs.append(normalize_batch_output(fn(group)))
            return concat_blocks(outs) if outs else npd
        return self._exchange(post, "map_groups")

    def _agg(self, col: Optional[str], reducer: Callable, out_col: str
             ) -> Dataset:
        key = self._key

        def post(block, j):
            npd = BlockAccessor(block).to_numpy()
            vals = np.asarray(npd[key])
            groups = np.unique(vals)
            out_keys, out_vals = [], []
            for g in groups:
                mask = vals == g
                out_keys.append(g)
                out_vals.append(reducer(
                    np.asarray(npd[col])[mask] if col else mask))
            return {key: np.asarray(out_keys),
                    out_col: np.asarray(out_vals)}
        return self._exchange(post, f"groupby-{out_col}")

    def count(self) -> Dataset:
        return self._agg(None, lambda mask: int(mask.sum()), "count()")

    def sum(self, col: str) -> Dataset:
        return self._agg(col, lambda v: v.sum(), f"sum({col})")

    def mean(self, col: str) -> Dataset:
        return self._agg(col, lambda v: v.mean(), f"mean({col})")

    def min(self, col: str) -> Dataset:
        return self._agg(col, lambda v: v.min(), f"min({col})")

    def max(self, col: str) -> Dataset:
        return self._agg(col, lambda v: v.max(), f"max({col})")

    def std(self, col: str) -> Dataset:
        return self._agg(col, lambda v: v.std(ddof=1) if v.size > 1
                         else 0.0, f"std({col})")
