"""Dataset — lazy, streaming, distributed datasets.

Reference analogue: ``python/ray/data/dataset.py:137`` (Dataset),
``read_api.py``, logical plan + streaming execution (SURVEY.md §2.3, A8).
A Dataset is a lazy plan: a block source plus a chain of operators;
consumption streams blocks through remote tasks with bounded in-flight
work (:mod:`raytpu.data.executor`). Blocks live in the object store; the
driver holds refs only.

Single-node simplifications (documented per method): global ops
(sort/repartition/random_shuffle) materialize; everything else streams.
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

import raytpu
from raytpu.data.block import (
    BlockAccessor,
    batch_format_view,
    block_from_rows,
    concat_blocks,
    normalize_batch_output,
)
from raytpu.data.executor import OpSpec, run_pipeline


class Dataset:
    def __init__(self, source_fn: Callable[[], Iterator], ops: List[OpSpec],
                 name: str = "dataset"):
        self._source_fn = source_fn  # () -> iterator of block refs
        self._ops = ops
        self._name = name

    # -- transforms (lazy) ----------------------------------------------------

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    num_cpus: float = 1.0, batch_size: Optional[int] = None,
                    fn_kwargs: Optional[dict] = None) -> "Dataset":
        """Apply fn to whole blocks (reference: ``Dataset.map_batches``).
        `batch_size=None` keeps source block boundaries (fastest)."""
        kw = fn_kwargs or {}

        def op(block):
            view = batch_format_view(block, batch_format)
            return normalize_batch_output(fn(view, **kw))

        ds = self._with_op(OpSpec(getattr(fn, "__name__", "map_batches"),
                                  op, num_cpus=num_cpus))
        if batch_size is not None:
            ds = ds._rechunk(batch_size)
        return ds

    def map(self, fn: Callable, *, num_cpus: float = 1.0) -> "Dataset":
        def op(block):
            rows = BlockAccessor(block).to_rows()
            return block_from_rows([fn(r) for r in rows])

        return self._with_op(OpSpec(getattr(fn, "__name__", "map"), op,
                                    num_cpus=num_cpus))

    def filter(self, fn: Callable) -> "Dataset":
        def op(block):
            rows = BlockAccessor(block).to_rows()
            return block_from_rows([r for r in rows if fn(r)])

        return self._with_op(OpSpec("filter", op))

    def flat_map(self, fn: Callable) -> "Dataset":
        def op(block):
            rows = BlockAccessor(block).to_rows()
            out = []
            for r in rows:
                out.extend(fn(r))
            return block_from_rows(out)

        return self._with_op(OpSpec("flat_map", op))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def op(batch):
            batch = dict(batch)
            batch[name] = fn(batch)
            return batch

        return self.map_batches(op, batch_format="numpy")

    def drop_columns(self, cols: Sequence[str]) -> "Dataset":
        def op(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return self.map_batches(op, batch_format="numpy")

    def select_columns(self, cols: Sequence[str]) -> "Dataset":
        def op(batch):
            return {k: batch[k] for k in cols}

        return self.map_batches(op, batch_format="numpy")

    def limit(self, n: int) -> "Dataset":
        parent = self

        def source():
            remaining = n
            for ref in parent._iter_block_refs():
                if remaining <= 0:
                    break
                block = raytpu.get(ref)
                rows = BlockAccessor(block).num_rows()
                if rows <= remaining:
                    remaining -= rows
                    yield ref
                else:
                    yield raytpu.put(
                        BlockAccessor(block).slice(0, remaining))
                    remaining = 0

        return Dataset(source, [], name=f"{self._name}.limit({n})")

    def union(self, *others: "Dataset") -> "Dataset":
        parents = [self, *others]

        def source():
            for p in parents:
                yield from p._iter_block_refs()

        return Dataset(source, [], name="union")

    def repartition(self, num_blocks: int) -> "Dataset":
        """Global op — materializes (all-to-all; reference repartition is a
        shuffle too)."""
        parent = self

        def source():
            blocks = [raytpu.get(r) for r in parent._iter_block_refs()]
            if not blocks:
                return
            whole = concat_blocks(blocks)
            total = BlockAccessor(whole).num_rows()
            per = max(1, -(-total // num_blocks))
            for i in range(num_blocks):
                lo, hi = i * per, min((i + 1) * per, total)
                if lo >= total:
                    break
                yield raytpu.put(BlockAccessor(whole).slice(lo, hi))

        return Dataset(source, [], name=f"{self._name}.repartition")

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global op — materializes and row-permutes."""
        parent = self

        def source():
            blocks = [raytpu.get(r) for r in parent._iter_block_refs()]
            if not blocks:
                return
            whole = BlockAccessor(concat_blocks(blocks))
            n = whole.num_rows()
            rng = np.random.default_rng(seed)
            perm = rng.permutation(n)
            npd = whole.to_numpy()
            shuffled = {k: np.asarray(v)[perm] for k, v in npd.items()}
            nblocks = max(1, len(blocks))
            per = -(-n // nblocks)
            for i in range(nblocks):
                lo, hi = i * per, min((i + 1) * per, n)
                if lo >= n:
                    break
                yield raytpu.put({k: v[lo:hi] for k, v in shuffled.items()})

        return Dataset(source, [], name=f"{self._name}.shuffle")

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Global op — materializes."""
        parent = self

        def source():
            blocks = [raytpu.get(r) for r in parent._iter_block_refs()]
            if not blocks:
                return
            whole = BlockAccessor(concat_blocks(blocks))
            npd = whole.to_numpy()
            order = np.argsort(npd[key], kind="stable")
            if descending:
                order = order[::-1]
            yield raytpu.put({k: np.asarray(v)[order]
                              for k, v in npd.items()})

        return Dataset(source, [], name=f"{self._name}.sort")

    # -- consumption ----------------------------------------------------------

    def _iter_block_refs(self) -> Iterator:
        return run_pipeline(self._source_fn(), self._ops)

    def iter_blocks(self) -> Iterator:
        for ref in self._iter_block_refs():
            yield raytpu.get(ref)

    def iter_rows(self) -> Iterator[dict]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator:
        """Re-chunk the block stream into fixed-size batches."""
        carry: List = []
        carry_rows = 0
        for block in self.iter_blocks():
            carry.append(block)
            carry_rows += BlockAccessor(block).num_rows()
            while carry_rows >= batch_size:
                whole = concat_blocks(carry)
                acc = BlockAccessor(whole)
                yield batch_format_view(acc.slice(0, batch_size),
                                        batch_format)
                rest = acc.slice(batch_size, acc.num_rows())
                carry = [rest]
                carry_rows = BlockAccessor(rest).num_rows()
        if carry_rows and not drop_last:
            whole = concat_blocks(carry)
            yield batch_format_view(whole, batch_format)

    def take(self, n: int = 20) -> List[dict]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[dict]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        return sum(BlockAccessor(b).num_rows() for b in self.iter_blocks())

    def sum(self, col: str):
        return sum(float(np.asarray(BlockAccessor(b).to_numpy()[col]).sum())
                   for b in self.iter_blocks())

    def mean(self, col: str):
        total, n = 0.0, 0
        for b in self.iter_blocks():
            arr = np.asarray(BlockAccessor(b).to_numpy()[col])
            total += float(arr.sum())
            n += arr.size
        return total / max(n, 1)

    def min(self, col: str):
        return min(float(np.asarray(BlockAccessor(b).to_numpy()[col]).min())
                   for b in self.iter_blocks())

    def max(self, col: str):
        return max(float(np.asarray(BlockAccessor(b).to_numpy()[col]).max())
                   for b in self.iter_blocks())

    def schema(self):
        for block in self.iter_blocks():
            return BlockAccessor(block).schema()
        return None

    def to_pandas(self):
        import pandas as pd

        frames = [BlockAccessor(b).to_pandas() for b in self.iter_blocks()]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def materialize(self) -> "Dataset":
        refs = list(self._iter_block_refs())

        def source():
            yield from refs

        return Dataset(source, [], name=f"{self._name}.materialized")

    def stats(self) -> dict:
        blocks = 0
        rows = 0
        nbytes = 0
        for b in self.iter_blocks():
            acc = BlockAccessor(b)
            blocks += 1
            rows += acc.num_rows()
            nbytes += acc.size_bytes()
        return {"blocks": blocks, "rows": rows, "bytes": nbytes}

    # -- train ingest ---------------------------------------------------------

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None) -> List["DataIterator"]:
        """n coordinated iterators over one pass of the stream (reference:
        ``Dataset.streaming_split``, ``dataset.py:1141`` — powered by a
        coordinator actor + OutputSplitter)."""
        coordinator = _SplitCoordinator.options(name=None).remote(
            self, n)
        return [DataIterator(coordinator, i) for i in range(n)]

    # -- writes ---------------------------------------------------------------

    def write_parquet(self, path: str) -> None:
        import os

        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            pq.write_table(BlockAccessor(block).to_arrow(),
                           f"{path}/part-{i:05d}.parquet")

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            BlockAccessor(block).to_pandas().to_csv(
                f"{path}/part-{i:05d}.csv", index=False)

    def write_json(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            BlockAccessor(block).to_pandas().to_json(
                f"{path}/part-{i:05d}.json", orient="records", lines=True)

    # -- internals ------------------------------------------------------------

    def _with_op(self, op: OpSpec) -> "Dataset":
        return Dataset(self._source_fn, [*self._ops, op], name=self._name)

    def _rechunk(self, rows_per_block: int) -> "Dataset":
        parent = self

        def source():
            for batch in parent.iter_batches(batch_size=rows_per_block):
                yield raytpu.put(batch)

        return Dataset(source, [], name=f"{self._name}.rechunk")

    def __repr__(self):
        ops = " -> ".join(op.name for op in self._ops) or "source"
        return f"Dataset({self._name}: {ops})"


@raytpu.remote(num_cpus=0)
class _SplitCoordinator:
    """Feeds n consumers from one pass (OutputSplitter analogue). Blocks
    are handed out round-robin; `equal=True` semantics approximated by
    per-consumer demand-driven pull."""

    def __init__(self, dataset: Dataset, n: int):
        self.iter = dataset._iter_block_refs()
        self.n = n
        self.buffers: List[List] = [[] for _ in range(n)]
        self.exhausted = False
        self.rr = 0

    def next_ref(self, split: int):
        """Next block ref for consumer `split`, or None at end of stream."""
        while not self.buffers[split] and not self.exhausted:
            try:
                ref = next(self.iter)
            except StopIteration:
                self.exhausted = True
                break
            self.buffers[self.rr].append(ref)
            self.rr = (self.rr + 1) % self.n
        if self.buffers[split]:
            return self.buffers[split].pop(0)
        return None


class DataIterator:
    """Per-worker streaming iterator (reference: ``DataIterator`` from
    ``streaming_split``; consumed in train loops via
    ``session.get_dataset_shard``)."""

    def __init__(self, coordinator, split: int):
        self._coordinator = coordinator
        self._split = split

    def iter_blocks(self):
        while True:
            # get() resolves the returned block ref one level, so this
            # yields the block value directly.
            block = raytpu.get(
                self._coordinator.next_ref.remote(self._split))
            if block is None:
                return
            yield block

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False):
        carry: List = []
        carry_rows = 0
        for block in self.iter_blocks():
            carry.append(block)
            carry_rows += BlockAccessor(block).num_rows()
            while carry_rows >= batch_size:
                whole = concat_blocks(carry)
                acc = BlockAccessor(whole)
                yield batch_format_view(acc.slice(0, batch_size),
                                        batch_format)
                rest = acc.slice(batch_size, acc.num_rows())
                carry = [rest]
                carry_rows = BlockAccessor(rest).num_rows()
        if carry_rows and not drop_last:
            yield batch_format_view(concat_blocks(carry), batch_format)

    def iter_rows(self):
        for block in self.iter_blocks():
            yield from BlockAccessor(block).to_rows()
