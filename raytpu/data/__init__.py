"""raytpu.data — lazy streaming datasets (reference: ``python/ray/data/``)."""

from raytpu.data.block import Block, BlockAccessor
from raytpu.data.dataset import DataIterator, Dataset, GroupedData
from raytpu.data.executor import ActorPoolStrategy, ResourceBudget
from raytpu.data.read_api import (
    from_arrow,
    from_generator,
    from_huggingface,
    from_items,
    from_jax,
    from_numpy,
    from_pandas,
    from_torch,
    range,  # noqa: A004
    range_tensor,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_numpy,
    read_orc,
    read_parquet,
    read_avro,
    read_sql,
    read_tfrecords,
    read_text,
    read_webdataset,
)

__all__ = [
    "Dataset",
    "DataIterator",
    "GroupedData",
    "ActorPoolStrategy",
    "ResourceBudget",
    "Block",
    "BlockAccessor",
    "range",
    "range_tensor",
    "from_generator",
    "from_huggingface",
    "from_items",
    "from_jax",
    "from_numpy",
    "from_pandas",
    "from_arrow",
    "from_torch",
    "read_binary_files",
    "read_csv",
    "read_images",
    "read_json",
    "read_numpy",
    "read_orc",
    "read_parquet",
    "read_avro",
    "read_sql",
    "read_tfrecords",
    "read_text",
    "read_webdataset",
]

from raytpu.util import usage_stats as _usage_stats

_usage_stats.record_library_usage("data")
