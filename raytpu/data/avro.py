"""Avro Object Container File codec, dependency-free.

Reference analogue: ``python/ray/data/datasource/avro_datasource.py``
(which leans on the ``fastavro`` wheel; not shipped in this image, so
the format is implemented directly). Scope: the OCF container (magic,
metadata, sync-marked blocks, null/deflate codecs) and the standard
binary encoding for records built from primitives, nullable unions,
enums, fixed, arrays, maps, and nested records — enough to round-trip
files produced by fastavro / avro-tools for tabular data.

Spec: https://avro.apache.org/docs/current/specification/ (the binary
encoding + object container file sections).
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Any, Dict, Iterator, List, Tuple

MAGIC = b"Obj\x01"


# -- zigzag varint (Avro int/long) ---------------------------------------

def _write_long(out: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)  # zigzag
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([bits | 0x80]))
        else:
            out.write(bytes([bits]))
            return


def _read_long(buf: io.BufferedIOBase) -> int:
    result = shift = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated avro varint")
        result |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
    return (result >> 1) ^ -(result & 1)  # un-zigzag


def _write_bytes(out: io.BytesIO, data: bytes) -> None:
    _write_long(out, len(data))
    out.write(data)


def _read_bytes(buf: io.BufferedIOBase) -> bytes:
    n = _read_long(buf)
    data = buf.read(n)
    if len(data) < n:
        raise EOFError("truncated avro bytes")
    return data


# -- datum encoding against a schema -------------------------------------

def _schema_type(schema) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def write_datum(out: io.BytesIO, schema, value) -> None:
    t = _schema_type(schema)
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        _write_long(out, int(value))
    elif t == "float":
        out.write(struct.pack("<f", float(value)))
    elif t == "double":
        out.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        _write_bytes(out, bytes(value))
    elif t == "string":
        _write_bytes(out, value.encode() if isinstance(value, str)
                     else bytes(value))
    elif t == "union":
        idx = _pick_union_branch(schema, value)
        _write_long(out, idx)
        write_datum(out, schema[idx], value)
    elif t == "record":
        # .get: infer_schema makes omitted keys nullable; honor that.
        for f in schema["fields"]:
            write_datum(out, f["type"], value.get(f["name"]))
    elif t == "enum":
        _write_long(out, schema["symbols"].index(value))
    elif t == "fixed":
        if len(value) != schema["size"]:
            raise ValueError(f"fixed {schema.get('name')}: expected "
                             f"{schema['size']} bytes, got {len(value)}")
        out.write(bytes(value))
    elif t == "array":
        items = list(value)
        if items:
            _write_long(out, len(items))
            for item in items:
                write_datum(out, schema["items"], item)
        _write_long(out, 0)
    elif t == "map":
        if value:
            _write_long(out, len(value))
            for k, v in value.items():
                _write_bytes(out, k.encode())
                write_datum(out, schema["values"], v)
        _write_long(out, 0)
    else:
        raise ValueError(f"unsupported avro type {t!r}")


def _pick_union_branch(union: List, value) -> int:
    def matches(branch) -> bool:
        bt = _schema_type(branch)
        if bt == "null":
            return value is None
        if bt == "boolean":
            return isinstance(value, bool)
        if bt in ("int", "long"):
            return isinstance(value, int) and not isinstance(value, bool)
        if bt in ("float", "double"):
            # ints are encodable as doubles (schema wins over the
            # Python type — a nullable-double column holding 1 must not
            # fail the write).
            return isinstance(value, (int, float)) \
                and not isinstance(value, bool)
        if bt == "string":
            return isinstance(value, str)
        if bt == "bytes":
            return isinstance(value, (bytes, bytearray))
        if bt == "record":
            return isinstance(value, dict)
        if bt == "array":
            return isinstance(value, (list, tuple))
        return False

    for i, branch in enumerate(union):
        if matches(branch):
            return i
    raise ValueError(f"value {value!r} matches no union branch {union}")


def read_datum(buf: io.BufferedIOBase, schema):
    t = _schema_type(schema)
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return _read_bytes(buf)
    if t == "string":
        return _read_bytes(buf).decode()
    if t == "union":
        return read_datum(buf, schema[_read_long(buf)])
    if t == "record":
        return {f["name"]: read_datum(buf, f["type"])
                for f in schema["fields"]}
    if t == "enum":
        return schema["symbols"][_read_long(buf)]
    if t == "fixed":
        return buf.read(schema["size"])
    if t == "array":
        out = []
        while True:
            n = _read_long(buf)
            if n == 0:
                return out
            if n < 0:  # block with byte-size prefix
                _read_long(buf)
                n = -n
            for _ in range(n):
                out.append(read_datum(buf, schema["items"]))
    if t == "map":
        out = {}
        while True:
            n = _read_long(buf)
            if n == 0:
                return out
            if n < 0:
                _read_long(buf)
                n = -n
            for _ in range(n):
                key = _read_bytes(buf).decode()
                out[key] = read_datum(buf, schema["values"])
    raise ValueError(f"unsupported avro type {t!r}")


# -- object container file ------------------------------------------------

def read_file(path: str) -> Tuple[dict, Iterator[dict]]:
    """Returns (schema, iterator of records)."""
    f = open(path, "rb")
    try:
        if f.read(4) != MAGIC:
            raise ValueError(
                f"{path} is not an avro object container file")
        meta: Dict[str, bytes] = {}
        while True:
            n = _read_long(f)
            if n == 0:
                break
            if n < 0:
                _read_long(f)
                n = -n
            for _ in range(n):
                key = _read_bytes(f).decode()
                meta[key] = _read_bytes(f)
        if "avro.schema" not in meta:
            raise ValueError(f"{path}: no avro.schema in file metadata")
        schema = json.loads(meta["avro.schema"])
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported avro codec {codec!r} "
                             f"(supported: null, deflate)")
        sync = f.read(16)
    except BaseException:
        f.close()
        raise

    def records() -> Iterator[dict]:
        try:
            while True:
                try:
                    count = _read_long(f)
                except EOFError:
                    return
                size = _read_long(f)
                data = f.read(size)
                if len(data) < size:
                    raise EOFError(f"truncated avro block in {path}")
                if codec == "deflate":
                    data = zlib.decompress(data, -15)
                block = io.BytesIO(data)
                for _ in range(count):
                    yield read_datum(block, schema)
                if f.read(16) != sync:
                    raise ValueError(f"avro sync marker mismatch in "
                                     f"{path}")
        finally:
            f.close()

    return schema, records()


def write_file(path: str, schema: dict, records: List[dict],
               codec: str = "null", sync: bytes = None) -> None:
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported avro codec {codec!r}")
    if sync is None:
        import os

        sync = os.urandom(16)  # per-file marker, as the spec intends
    with open(path, "wb") as f:
        f.write(MAGIC)
        head = io.BytesIO()
        _write_long(head, 2)
        _write_bytes(head, b"avro.schema")
        _write_bytes(head, json.dumps(schema).encode())
        _write_bytes(head, b"avro.codec")
        _write_bytes(head, codec.encode())
        _write_long(head, 0)
        f.write(head.getvalue())
        f.write(sync)
        if records:
            body = io.BytesIO()
            for r in records:
                write_datum(body, schema, r)
            data = body.getvalue()
            if codec == "deflate":
                data = zlib.compress(data)[2:-4]  # raw deflate, no adler
            block = io.BytesIO()
            _write_long(block, len(records))
            _write_long(block, len(data))
            f.write(block.getvalue())
            f.write(data)
            f.write(sync)


def infer_schema(rows: List[dict], name: str = "raytpu_record") -> dict:
    """Record schema from sample rows: long/double/string/bytes/boolean
    primitives, nullable (union with null) when any sample is None."""
    import numpy as np

    fields = []
    keys: List[str] = []
    for r in rows:
        for k in r:
            if k not in keys:
                keys.append(k)
    for k in keys:
        sample = [r.get(k) for r in rows]
        types = set()
        for v in sample:
            if v is None:
                types.add("null")
            elif isinstance(v, bool):
                types.add("boolean")
            elif isinstance(v, (int, np.integer)):
                types.add("long")
            elif isinstance(v, (float, np.floating)):
                types.add("double")
            elif isinstance(v, str):
                types.add("string")
            elif isinstance(v, (bytes, bytearray)):
                types.add("bytes")
            else:
                raise TypeError(
                    f"column {k!r}: cannot infer avro type for "
                    f"{type(v).__name__}; pass an explicit schema")
        if {"long", "double"} <= types:  # mixed numerics widen to double
            types = (types - {"long"})
        non_null = sorted(types - {"null"})
        if len(non_null) > 1:
            raise TypeError(f"column {k!r}: mixed types {non_null}; "
                            f"pass an explicit schema")
        base = non_null[0] if non_null else "null"
        fields.append({"name": k,
                       "type": ["null", base] if "null" in types
                       and base != "null" else base})
    return {"type": "record", "name": name, "fields": fields}
