"""TFRecord container + tf.train.Example codec, dependency-free.

Reference analogue: ``python/ray/data/datasource/tfrecords_datasource.py``
(read/write of TFRecord files holding ``tf.train.Example`` protos). The
reference leans on tensorflow / ``tfx-bsl`` for parsing; neither ships
in this image, so both layers are implemented directly:

- The TFRecord framing: ``[len u64le][masked-crc32c(len) u32le][data]
  [masked-crc32c(data) u32le]`` per record (the classic TFRecordWriter
  layout), with table-driven CRC32C (Castagnoli) in pure Python.
- The ``Example`` proto wire format, hand-rolled for its tiny fixed
  schema: Example{Features{map<string, Feature>}} where Feature is one
  of BytesList / FloatList(packed) / Int64List(packed).

Scope: enough to round-trip real TFRecord/Example files produced by
TensorFlow tooling; not a general protobuf implementation.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List

import numpy as np

# -- CRC32C (Castagnoli, reflected poly 0x82F63B78) ----------------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    # Built into a local list and published with one atomic assignment:
    # concurrent first callers (parallel read tasks run as threads in
    # the local backend) must never observe a partially built table.
    global _CRC_TABLE
    if not _CRC_TABLE:
        table = []
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- TFRecord framing ----------------------------------------------------

def write_records(path: str, records: List[bytes]) -> None:
    with open(path, "wb") as f:
        for data in records:
            length = struct.pack("<Q", len(data))
            f.write(length)
            f.write(struct.pack("<I", _masked_crc(length)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))


def read_records(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,), (len_crc,) = (struct.unpack("<Q", header[:8]),
                                     struct.unpack("<I", header[8:]))
            if _masked_crc(header[:8]) != len_crc:
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"truncated TFRecord data in {path}")
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4:
                raise ValueError(f"truncated TFRecord data crc in {path}")
            (data_crc,) = struct.unpack("<I", crc_bytes)
            if _masked_crc(data) != data_crc:
                raise ValueError(f"corrupt TFRecord data crc in {path}")
            yield data


# -- minimal protobuf wire helpers ---------------------------------------

def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _ld(field: int, payload: bytes) -> bytes:  # length-delimited
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _fields(buf: bytes) -> Iterator[tuple]:
    """Yield (field_number, wire_type, value) over a message buffer."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:  # fixed32
            val = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:  # fixed64
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield field, wt, val


def _zigzag_i64(v: int) -> int:
    """int64 varints are two's-complement on the wire (not zigzag)."""
    return v - (1 << 64) if v >= 1 << 63 else v


# -- tf.train.Example codec ----------------------------------------------

def encode_example(features: Dict[str, object]) -> bytes:
    """Dict -> serialized Example. Values: bytes/str -> BytesList,
    float arrays -> FloatList, int arrays -> Int64List; lists/ndarrays
    become multi-value features."""
    feats = bytearray()
    for name, value in features.items():
        if isinstance(value, (bytes, str)):
            values = [value]
        elif isinstance(value, np.ndarray):
            values = list(value.reshape(-1))
        elif isinstance(value, (list, tuple)):
            values = list(value)
        else:
            values = [value]
        if not values:
            feature = _ld(3, b"")  # empty Int64List
        elif isinstance(values[0], (bytes, str)):
            bl = bytearray()
            for v in values:
                bl += _ld(1, v.encode() if isinstance(v, str) else v)
            feature = _ld(1, bytes(bl))
        elif isinstance(values[0], (float, np.floating)):
            packed = struct.pack(f"<{len(values)}f",
                                 *[float(v) for v in values])
            feature = _ld(2, _ld(1, packed))
        elif isinstance(values[0], (int, np.integer)):
            pv = bytearray()
            for v in values:
                pv += _varint(int(v) & 0xFFFFFFFFFFFFFFFF)
            feature = _ld(3, _ld(1, bytes(pv)))
        else:
            raise TypeError(f"feature {name!r}: unsupported value type "
                            f"{type(values[0]).__name__}")
        entry = _ld(1, name.encode()) + _ld(2, feature)
        feats += _ld(1, entry)  # map entry on Features.feature
    return _ld(1, bytes(feats))  # Example.features


def decode_example(data: bytes) -> Dict[str, object]:
    """Serialized Example -> {name: scalar or list}. Single-value
    features decode to scalars (the common case for tabular data);
    multi-value features decode to lists."""
    out: Dict[str, object] = {}
    for f, _, features_buf in _fields(data):
        if f != 1:
            continue
        for f2, _, entry in _fields(features_buf):
            if f2 != 1:
                continue
            name, feature = None, b""
            for f3, _, v in _fields(entry):
                if f3 == 1:
                    name = v.decode()
                elif f3 == 2:
                    feature = v
            if name is None:
                continue
            values: List[object] = []
            for f4, _, lst in _fields(feature):
                if f4 == 1:  # BytesList
                    values = [v for f5, _, v in _fields(lst) if f5 == 1]
                elif f4 == 2:  # FloatList (packed or not)
                    for f5, wt5, v in _fields(lst):
                        if f5 != 1:
                            continue
                        if wt5 == 2:  # packed
                            values.extend(struct.unpack(
                                f"<{len(v) // 4}f", v))
                        else:  # unpacked fixed32
                            values.append(struct.unpack("<f", v)[0])
                elif f4 == 3:  # Int64List (packed or not)
                    for f5, wt5, v in _fields(lst):
                        if f5 != 1:
                            continue
                        if wt5 == 2:  # packed varints
                            pos = 0
                            while pos < len(v):
                                iv, pos = _read_varint(v, pos)
                                values.append(_zigzag_i64(iv))
                        else:
                            values.append(_zigzag_i64(v))
            out[name] = values[0] if len(values) == 1 else values
    return out
