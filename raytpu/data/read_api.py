"""Dataset sources (reference: ``python/ray/data/read_api.py`` + the 38
datasource modules under ``python/ray/data/datasource/`` — the common
file-based ones re-implemented; exotic connectors are later-round work)."""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import raytpu
from raytpu.data.block import block_from_rows
from raytpu.data.dataset import Dataset


def range(n: int, *, blocks: int = 8) -> Dataset:  # noqa: A001
    """Integers [0, n) as column 'id' (reference: ``ray.data.range``)."""
    import builtins

    blocks = max(1, min(blocks, n or 1))

    def source():
        per = -(-n // blocks)
        for i in builtins.range(blocks):
            lo, hi = i * per, min((i + 1) * per, n)
            if lo >= n:
                break
            yield raytpu.put({"id": np.arange(lo, hi, dtype=np.int64)})

    return Dataset(source, [], name=f"range({n})")


def range_tensor(n: int, *, shape=(1,), blocks: int = 8) -> Dataset:
    blocks = max(1, min(blocks, n or 1))

    def source():
        import builtins

        per = -(-n // blocks)
        for i in builtins.range(blocks):
            lo, hi = i * per, min((i + 1) * per, n)
            if lo >= n:
                break
            count = hi - lo
            data = np.arange(lo, hi, dtype=np.float32).reshape(
                (count,) + (1,) * len(shape)) * np.ones((1,) + tuple(shape),
                                                        np.float32)
            yield raytpu.put({"data": data})

    return Dataset(source, [], name=f"range_tensor({n})")


def from_generator(generator, *, name: str = "from_generator") -> Dataset:
    """Dataset over a streaming task's output.

    ``generator`` is an :class:`~raytpu.ObjectRefGenerator` (from a
    ``num_returns="streaming"`` task) or a zero-arg callable returning one.
    Each yielded chunk (dict of arrays, list of rows, arrow table, pandas
    frame) becomes one block — consumable by ``iter_batches`` while the
    producer task is still running (reference: Ray Data over streaming
    generators, ``python/ray/data/read_api.py`` iterator sources).

    A bare generator is single-consumption (like any iterator); pass a
    callable to make the dataset re-iterable.
    """
    from raytpu.data.block import normalize_batch_output
    from raytpu.data.executor import OpSpec

    def source():
        gen = generator() if callable(generator) else generator
        for ref in gen:
            yield ref

    ds = Dataset(source, [], name=name)
    return ds._with_op(OpSpec("normalize", normalize_batch_output))


def from_items(items: List[Any], *, blocks: int = 8) -> Dataset:
    items = list(items)
    blocks = max(1, min(blocks, len(items) or 1))

    def source():
        import builtins

        per = -(-len(items) // blocks)
        for i in builtins.range(blocks):
            chunk = items[i * per: (i + 1) * per]
            if not chunk:
                break
            rows = [x if isinstance(x, dict) else {"item": x} for x in chunk]
            yield raytpu.put(block_from_rows(rows))

    return Dataset(source, [], name="from_items")


def from_numpy(arrays: Dict[str, np.ndarray], *, blocks: int = 1) -> Dataset:
    def source():
        import builtins

        n = len(next(iter(arrays.values())))
        per = -(-n // blocks)
        for i in builtins.range(blocks):
            lo, hi = i * per, min((i + 1) * per, n)
            if lo >= n:
                break
            yield raytpu.put({k: np.asarray(v)[lo:hi]
                              for k, v in arrays.items()})

    return Dataset(source, [], name="from_numpy")


def from_pandas(df) -> Dataset:
    import pyarrow as pa

    table = pa.Table.from_pandas(df, preserve_index=False)

    def source():
        yield raytpu.put(table)

    return Dataset(source, [], name="from_pandas")


def from_arrow(table) -> Dataset:
    def source():
        yield raytpu.put(table)

    return Dataset(source, [], name="from_arrow")


def _expand_paths(paths, suffix: str, recursive: bool = False
                  ) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            if recursive:
                # Partitioned layouts nest files under col=value/.
                files.extend(sorted(_glob.glob(
                    os.path.join(p, f"**/*{suffix}"), recursive=True)))
            else:
                files.extend(sorted(_glob.glob(
                    os.path.join(p, f"*{suffix}"))))
        elif any(ch in p for ch in "*?["):
            files.extend(sorted(_glob.glob(p)))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no {suffix} files under {paths}")
    return files


def read_parquet(paths, *, columns: Optional[Sequence[str]] = None,
                 partitioning: Optional[str] = "hive") -> Dataset:
    """One remote read task per file — IO parallelism rides the task
    fabric (reference: parquet datasource). Hive-partitioned layouts
    (``root/col=value/.../part.parquet``, e.g. from
    ``write_parquet(partition_cols=...)`` or Spark) are detected by
    default: ``col`` comes back as a column parsed from the path
    (int/float/None/string inferred); ``partitioning=None`` disables.
    """
    files = _expand_paths(paths, ".parquet", recursive=True)
    # Partition values are resolved at PLANNING time, driver-side:
    # segments are parsed only BELOW the user-passed read roots (a
    # col=value directory above the dataset must not inject columns),
    # and one partition schema is typed across ALL files (a dataset
    # with year=2024 and year=unknown reads year as string everywhere,
    # never int-in-one-file/str-in-another).
    part_vals: Dict[str, Dict[str, Any]] = {}
    if partitioning == "hive":
        roots = [p for p in ([paths] if isinstance(paths, str)
                             else list(paths)) if os.path.isdir(p)]
        raw = {f: _hive_raw_segments(f, roots) for f in files}
        part_vals = _type_partition_values(raw)

    @raytpu.remote(name="data::read_parquet")
    def read_one(path, parts):
        import pyarrow as pa
        import pyarrow.parquet as pq

        file_cols = None
        if columns:
            file_cols = [c for c in columns if c not in parts]
        table = pq.read_table(path, columns=file_cols)
        for k, v in parts.items():
            if columns and k not in columns:
                continue
            table = table.append_column(
                k, pa.array([v] * len(table)))
        return table

    def source():
        for f in files:
            yield read_one.remote(f, part_vals.get(f, {}))

    return Dataset(source, [], name="read_parquet")


HIVE_NULL = "__HIVE_DEFAULT_PARTITION__"


def _hive_raw_segments(path: str, roots: List[str]) -> Dict[str, str]:
    """``key=value`` path segments BELOW the matching read root, raw
    (unquoted string) values; {} when the file is under no known root."""
    import urllib.parse

    rel = None
    for root in sorted(roots, key=len, reverse=True):
        r = root.rstrip(os.sep) + os.sep
        if path.startswith(r):
            rel = path[len(r):]
            break
    if rel is None:
        return {}
    out: Dict[str, str] = {}
    for seg in rel.split(os.sep)[:-1]:
        if "=" in seg:
            k, _, v = seg.partition("=")
            out[k] = urllib.parse.unquote(v)
    return out


def _type_partition_values(raw: Dict[str, Dict[str, str]]
                           ) -> Dict[str, Dict[str, Any]]:
    """One type per partition key across the whole dataset: int if
    every value parses as int, else float if every value parses, else
    string. ``__HIVE_DEFAULT_PARTITION__`` decodes to None."""
    def parses(vals, cast) -> bool:
        for v in vals:
            try:
                cast(v)
            except ValueError:
                return False
        return True

    by_key: Dict[str, List[str]] = {}
    for parts in raw.values():
        for k, v in parts.items():
            if v != HIVE_NULL:
                by_key.setdefault(k, []).append(v)
    casts: Dict[str, Any] = {}
    for k, vals in by_key.items():
        casts[k] = (int if parses(vals, int)
                    else float if parses(vals, float) else str)
    return {f: {k: (None if v == HIVE_NULL
                    else casts.get(k, str)(v))
                for k, v in parts.items()}
            for f, parts in raw.items()}


def read_csv(paths, **read_kwargs) -> Dataset:
    files = _expand_paths(paths, ".csv")

    @raytpu.remote(name="data::read_csv")
    def read_one(path):
        import pyarrow as pa
        import pyarrow.csv as pacsv

        return pacsv.read_csv(path)

    def source():
        for f in files:
            yield read_one.remote(f)

    return Dataset(source, [], name="read_csv")


def read_json(paths, **read_kwargs) -> Dataset:
    files = _expand_paths(paths, ".json")

    @raytpu.remote(name="data::read_json")
    def read_one(path):
        import pyarrow.json as pajson

        return pajson.read_json(path)

    def source():
        for f in files:
            yield read_one.remote(f)

    return Dataset(source, [], name="read_json")


def read_text(paths) -> Dataset:
    files = _expand_paths(paths, "")

    @raytpu.remote(name="data::read_text")
    def read_one(path):
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        return block_from_rows([{"text": ln} for ln in lines])

    def source():
        for f in files:
            yield read_one.remote(f)

    return Dataset(source, [], name="read_text")


def read_numpy(paths) -> Dataset:
    """One block per ``.npy`` file (reference: numpy datasource)."""
    files = _expand_paths(paths, ".npy")

    @raytpu.remote(name="data::read_numpy")
    def read_one(path):
        arr = np.load(path)
        return {"data": arr}

    def source():
        for f in files:
            yield read_one.remote(f)

    return Dataset(source, [], name="read_numpy")


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    """Whole files as ``bytes`` rows (reference: binary datasource —
    the image/audio/file-blob workhorse)."""
    files = _expand_paths(paths, "")

    @raytpu.remote(name="data::read_binary")
    def read_one(path):
        with open(path, "rb") as f:
            data = f.read()
        row = {"bytes": data}
        if include_paths:
            row["path"] = path
        return block_from_rows([row])

    def source():
        for f in files:
            yield read_one.remote(f)

    return Dataset(source, [], name="read_binary_files")


def from_torch(torch_dataset, *, blocks: int = 8) -> Dataset:
    """A map-style ``torch.utils.data.Dataset`` as a raytpu Dataset
    (reference: ``ray.data.from_torch``). Items convert via numpy; rows
    are ``{"item": value}`` unless the item is a dict."""

    n = len(torch_dataset)
    blocks = max(1, min(blocks, n or 1))

    def _to_host(v):
        try:
            import torch

            if isinstance(v, torch.Tensor):
                v = v.detach().cpu().numpy()
        except ImportError:  # pragma: no cover
            pass
        if isinstance(v, np.ndarray) and v.ndim == 0:
            return v.item()
        return v

    def _to_row(x):
        if isinstance(x, dict):
            return {k: _to_host(v) for k, v in x.items()}
        if isinstance(x, (tuple, list)):
            if len(x) == 1:
                return {"item": _to_host(x[0])}
            return {f"item_{i}": _to_host(v) for i, v in enumerate(x)}
        return {"item": _to_host(x)}

    def source():
        import builtins

        per = -(-n // blocks)
        for i in builtins.range(blocks):
            lo, hi = i * per, min((i + 1) * per, n)
            if lo >= n:
                break
            rows = [_to_row(torch_dataset[j]) for j in builtins.range(lo, hi)]
            yield raytpu.put(block_from_rows(rows))

    return Dataset(source, [], name="from_torch")


def from_jax(arrays, *, blocks: int = 1) -> Dataset:
    """Dict of jax arrays -> Dataset (host transfer happens once, at
    block creation; the TPU-side consumer is ``iter_jax_batches``)."""
    host = {k: np.asarray(v) for k, v in arrays.items()}
    return from_numpy(host, blocks=blocks)


def read_sql(sql: str, connection_factory, *, blocks: int = 1,
             partition_column: Optional[str] = None,
             num_partitions: Optional[int] = None,
             lower_bound=None, upper_bound=None) -> Dataset:
    """Rows of a SQL query as a Dataset (reference: SQL datasource,
    ``python/ray/data/datasource/sql_datasource.py``).

    ``connection_factory`` is a zero-arg callable returning a DBAPI
    connection (e.g. ``lambda: sqlite3.connect(path)``) — it runs inside
    the read task, so the connection itself never serializes.

    **Partitioned reads**: with ``partition_column`` +
    ``num_partitions``, the query runs as N PARALLEL read tasks, each
    executing a range-predicate sub-query

        ``SELECT * FROM (<sql>) WHERE col >= lo AND col < hi``

    (JDBC/Spark-style pushdown: each partition moves only its own rows).
    ``lower_bound``/``upper_bound`` set the partition STRIDE only — they
    never filter: the first partition's lower and the last partition's
    upper predicate are open-ended (Spark JDBC semantics), and NULL
    partition-column rows ride the last partition's ``IS NULL`` arm,
    so every row lands in exactly one partition. When bounds are
    omitted a MIN/MAX pre-query derives them; the column must be
    numeric-ish.
    """
    if partition_column is None:
        @raytpu.remote(name="data::read_sql")
        def read_all():
            conn = connection_factory()
            try:
                # DB-API 2.0 (conn.execute is sqlite-only)
                cur = conn.cursor()
                cur.execute(sql)
                cols = [d[0] for d in cur.description]
                rows = [dict(zip(cols, r)) for r in cur.fetchall()]
            finally:
                conn.close()
            return block_from_rows(rows)

        def source():
            yield read_all.remote()

        ds = Dataset(source, [], name="read_sql")
        return ds.repartition(blocks) if blocks > 1 else ds

    n = int(num_partitions or blocks or 1)
    if n < 1:
        raise ValueError("num_partitions must be >= 1")
    col = str(partition_column)

    if lower_bound is None or upper_bound is None:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT MIN({col}), MAX({col}) "  # noqa: S608
                        f"FROM ({sql}) AS raytpu_bounds")
            lo_db, hi_db = cur.fetchone()
        finally:
            conn.close()
        if lo_db is None:
            # Empty result set OR every row has a NULL partition column:
            # a single unpartitioned read covers both correctly.
            return read_sql(sql, connection_factory, blocks=1)
        lower_bound = lo_db if lower_bound is None else lower_bound
        upper_bound = hi_db if upper_bound is None else upper_bound

    def _literal(x) -> str:
        # Bounds are embedded as validated NUMERIC literals, not bind
        # params: DBAPI paramstyle varies by driver (qmark vs pyformat
        # vs ...) and a literal number is portable across all of them.
        if isinstance(x, bool) or not isinstance(x, (int, float,
                                                     np.integer,
                                                     np.floating)):
            raise TypeError(
                f"partition_column bounds must be numeric, got "
                f"{type(x).__name__} ({x!r}); use explicit numeric "
                f"lower_bound/upper_bound (e.g. epoch seconds for "
                f"time columns)")
        return repr(int(x) if isinstance(x, np.integer) else
                    float(x) if isinstance(x, np.floating) else x)

    @raytpu.remote(name="data::read_sql_partition")
    def read_partition(lo, hi, first: bool, last: bool):
        # JDBC/Spark semantics: bounds set the STRIDE, they never
        # filter — the first partition's lower and the last partition's
        # upper predicate are open-ended, and the last also adopts
        # NULL-column rows, so every row lands in exactly one partition.
        clauses = []
        if not first:
            clauses.append(f"{col} >= {_literal(lo)}")
        if not last:
            clauses.append(f"{col} < {_literal(hi)}")
        pred = " AND ".join(clauses) if clauses else "1=1"
        if last:
            pred = f"({pred}) OR {col} IS NULL"
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT * FROM ({sql}) AS raytpu_part "  # noqa: S608
                        f"WHERE {pred}")
            cols = [d[0] for d in cur.description]
            rows = [dict(zip(cols, r)) for r in cur.fetchall()]
        finally:
            conn.close()
        return block_from_rows(rows)

    # Validate bounds eagerly (a TypeError at .remote() execution time
    # would surface as a task error instead of at the call site).
    _literal(lower_bound), _literal(upper_bound)
    integral = isinstance(lower_bound, int) and isinstance(upper_bound, int)

    def _boundary(i: int):
        # Integer bounds use pure integer arithmetic: float strides lose
        # precision past 2**53 (e.g. snowflake ids) and would misplace
        # boundary rows between partitions.
        if integral:
            return lower_bound + (upper_bound - lower_bound) * i // n
        lo_f, hi_f = float(lower_bound), float(upper_bound)
        return lo_f + (hi_f - lo_f) * i / n

    def source():
        import builtins

        for i in builtins.range(n):
            yield read_partition.remote(_boundary(i), _boundary(i + 1),
                                        i == 0, i == n - 1)

    return Dataset(source, [], name="read_sql")


def read_images(paths, *, size=None, mode: str = "RGB",
                include_paths: bool = False) -> Dataset:
    """Image files as float32 arrays via PIL (reference: image
    datasource). ``size=(w, h)`` resizes; one block per file. Without
    ``size`` the corpus must share dimensions for any batching path
    (``iter_batches``/``take_batch`` concatenate [1,H,W,C] arrays);
    mixed-size corpora should pass ``size=``."""
    exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")
    files = [f for f in _expand_paths(paths, "")
             if f.lower().endswith(exts)]
    if not files:
        raise FileNotFoundError(f"no image files under {paths}")

    @raytpu.remote(name="data::read_images")
    def read_one(path):
        from PIL import Image

        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize(tuple(size))
        arr = np.asarray(img, dtype=np.float32)[None]  # [1, H, W, C]
        block = {"image": arr}
        if include_paths:
            block["path"] = np.asarray([path])
        return block

    def source():
        for f in files:
            yield read_one.remote(f)

    return Dataset(source, [], name="read_images")


def read_webdataset(paths) -> Dataset:
    """WebDataset-style tar shards: files grouped by key (basename
    before the first dot), one row per key with a column per extension
    (reference: webdataset datasource). Text-like members decode to
    str; everything else stays bytes."""
    files = _expand_paths(paths, ".tar")

    @raytpu.remote(name="data::read_webdataset")
    def read_shard(path):
        import tarfile

        samples: Dict[str, dict] = {}
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                key, _, ext = base.partition(".")
                data = tf.extractfile(member).read()
                if ext in ("txt", "json", "cls", "csv"):
                    try:
                        data = data.decode("utf-8")
                    except UnicodeDecodeError:
                        pass
                samples.setdefault(key, {"__key__": key})[ext] = data
        # Samples may carry different extension sets; block columns are
        # the union, absent members become None.
        all_keys = sorted({k for s in samples.values() for k in s})
        rows = [{k: samples[key].get(k) for k in all_keys}
                for key in sorted(samples)]
        return block_from_rows(rows)

    def source():
        for f in files:
            yield read_shard.remote(f)

    return Dataset(source, [], name="read_webdataset")


def read_tfrecords(paths, *, raw: bool = False) -> Dataset:
    """TFRecord files of ``tf.train.Example`` protos as a Dataset, one
    block per file read in parallel (reference: tfrecords datasource;
    codec notes in :mod:`raytpu.data.tfrecord`). ``raw=True`` skips the
    Example parse and yields one ``{"data": bytes}`` row per record."""
    files = _expand_paths(paths, ".tfrecord")

    @raytpu.remote(name="data::read_tfrecords")
    def read_file(path):
        from raytpu.data.tfrecord import decode_example, read_records

        if raw:
            rows = [{"data": rec} for rec in read_records(path)]
        else:
            rows = [decode_example(rec) for rec in read_records(path)]
        return block_from_rows(rows)

    def source():
        for f in files:
            yield read_file.remote(f)

    return Dataset(source, [], name="read_tfrecords")


def read_orc(paths, *, columns: Optional[Sequence[str]] = None) -> Dataset:
    """ORC files as a Dataset, one remote read task per file
    (reference analogue: ``python/ray/data/read_api.py`` ``read_orc``
    via the ORC datasource; here pyarrow.orc does the codec work and IO
    parallelism rides the task fabric)."""
    files = _expand_paths(paths, ".orc")

    @raytpu.remote(name="data::read_orc")
    def read_one(path):
        from pyarrow import orc

        return orc.read_table(path, columns=list(columns)
                              if columns else None)

    def source():
        for f in files:
            yield read_one.remote(f)

    return Dataset(source, [], name="read_orc")


def from_huggingface(hf_dataset, *, blocks: int = 8) -> Dataset:
    """A HuggingFace ``datasets.Dataset`` as a Dataset (reference
    analogue: ``python/ray/data/read_api.py`` ``from_huggingface``).

    The HF dataset is arrow-backed; each block is a contiguous shard
    converted to an arrow table. ``IterableDataset`` (streaming) is not
    supported — materialize it first (mirrors the reference's
    constraint for non-streaming parallelism).
    """
    try:
        import datasets as hf
    except ImportError as e:  # pragma: no cover
        raise ImportError("from_huggingface requires the 'datasets' "
                          "package") from e
    if isinstance(hf_dataset, hf.IterableDataset):
        raise TypeError(
            "from_huggingface needs a materialized datasets.Dataset; "
            "streaming IterableDataset is unsupported (use "
            ".take()/.to_list() or load without streaming=True)")
    if not isinstance(hf_dataset, hf.Dataset):
        raise TypeError(f"expected datasets.Dataset, got "
                        f"{type(hf_dataset).__name__}")
    # A shuffled/filtered/selected HF dataset is a view: an indices
    # mapping over the unmodified arrow table. Materialize the view
    # first, or every shard's .data.table would be the FULL original
    # table (duplicated, wrong-order rows).
    if getattr(hf_dataset, "_indices", None) is not None:
        hf_dataset = hf_dataset.flatten_indices()
    n = len(hf_dataset)
    blocks = max(1, min(blocks, n) if n else 1)

    def source():
        import builtins

        for i in builtins.range(blocks):
            shard = hf_dataset.shard(num_shards=blocks, index=i,
                                     contiguous=True)
            yield raytpu.put(shard.data.table.combine_chunks())

    return Dataset(source, [], name="from_huggingface")


def read_avro(paths) -> Dataset:
    """Avro object container files as a Dataset, one block per file
    read in parallel (reference: avro datasource; dependency-free OCF
    codec in :mod:`raytpu.data.avro` — null + deflate codecs)."""
    files = _expand_paths(paths, ".avro")

    @raytpu.remote(name="data::read_avro")
    def read_one(path):
        from raytpu.data.avro import read_file

        _, records = read_file(path)
        return block_from_rows(list(records))

    def source():
        for f in files:
            yield read_one.remote(f)

    return Dataset(source, [], name="read_avro")
