"""raytpu.dashboard — server-rendered cluster dashboard."""

from raytpu.dashboard.app import DashboardServer

__all__ = ["DashboardServer"]
