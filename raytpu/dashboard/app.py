"""Dashboard v1: one aiohttp app over the state API + metrics + timeline.

Reference analogue: ``dashboard/head.py:81`` / ``dashboard/agent.py:28``
— shrunk to the server-rendered essentials: cluster summary, node /
actor / task / placement-group tables, object-store summary, a
chrome-trace timeline download, and Prometheus metrics. No React build;
every page is generated from the live state API the CLI already uses, so
the dashboard works against any cluster the driver can connect to.

Start via ``raytpu dashboard --address tcp://HEAD`` or embed
:class:`DashboardServer` in a driver process.
"""

from __future__ import annotations

import asyncio
import html
import json
import threading
from typing import Any, Dict, List, Optional

_PAGE = """<!doctype html>
<html><head><title>raytpu dashboard</title>
<meta http-equiv="refresh" content="5">
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2em; color: #222; }}
 h1 {{ font-size: 1.4em; }} h2 {{ font-size: 1.1em; margin-top: 1.5em; }}
 table {{ border-collapse: collapse; min-width: 40em; }}
 th, td {{ border: 1px solid #ccc; padding: 4px 10px; text-align: left;
           font-size: 0.9em; }}
 th {{ background: #f0f0f0; }}
 .pill {{ padding: 1px 8px; border-radius: 8px; font-size: 0.85em; }}
 .ok {{ background: #d8f5d8; }} .bad {{ background: #f5d8d8; }}
 nav a {{ margin-right: 1em; }}
</style></head>
<body>
<h1>raytpu dashboard</h1>
<nav><a href="/">summary</a><a href="/timeline">timeline.json</a>
<a href="/metrics">metrics</a><a href="/api/summary">api</a></nav>
{body}
</body></html>"""


def _table(headers: List[str], rows: List[List[Any]]) -> str:
    head = "".join(f"<th>{html.escape(str(h))}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{cell}</td>" for cell in row) + "</tr>"
        for row in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


def _pill(ok: bool, text: str) -> str:
    return f'<span class="pill {"ok" if ok else "bad"}">{text}</span>'


class DashboardServer:
    """Serves the dashboard for whatever cluster the current raytpu
    session is connected to (call ``raytpu.init`` first)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self._host = host
        self._port = port
        self._runner = None
        self._thread: Optional[threading.Thread] = None
        self.url: Optional[str] = None

    # -- data --------------------------------------------------------------

    def _snapshot(self) -> Dict[str, Any]:
        from raytpu.state import api as state

        out: Dict[str, Any] = {}
        for key, fn in (
            ("nodes", state.list_nodes),
            # list_actors wraps its rows with partial/errors markers;
            # the dashboard sections keep the flat-list shape.
            ("actors", lambda: state.list_actors().get("actors", [])),
            ("tasks", lambda: state.list_tasks()),
            ("placement_groups", state.list_placement_groups),
            ("task_summary", state.summarize_tasks),
            ("objects", state.object_summary),
            ("events", lambda: state.list_events(limit=50)),
        ):
            try:
                out[key] = fn()
            except Exception as e:  # degrade per-section, never 500
                out[key] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # -- pages -------------------------------------------------------------

    def _render_summary(self) -> str:
        snap = self._snapshot()
        parts = []

        nodes = snap["nodes"]
        if isinstance(nodes, list):
            alive = sum(1 for n in nodes if n.get("Alive"))
            parts.append(f"<h2>Nodes ({alive}/{len(nodes)} alive)</h2>")
            parts.append(_table(
                ["node", "alive", "address", "resources", "available"],
                [[n.get("NodeID", "")[:12],
                  _pill(bool(n.get("Alive")),
                        "alive" if n.get("Alive") else "dead"),
                  html.escape(str(n.get("Address", ""))),
                  html.escape(json.dumps(n.get("Resources", {}))),
                  html.escape(json.dumps(n.get("Available", {})))]
                 for n in nodes]))

        ts = snap["task_summary"]
        if isinstance(ts, dict) and "error" not in ts:
            parts.append("<h2>Tasks</h2>")
            parts.append(_table(["state", "count"],
                                [[html.escape(k), v]
                                 for k, v in sorted(ts.items())]))

        actors = snap["actors"]
        if isinstance(actors, list):
            parts.append(f"<h2>Actors ({len(actors)})</h2>")
            parts.append(_table(
                ["actor", "name", "state", "node"],
                [[a.get("actor_id", "")[:12],
                  html.escape(str(a.get("name") or "")),
                  _pill(a.get("state") == "ALIVE",
                        str(a.get("state", "?"))),
                  str(a.get("node_id", ""))[:12]]
                 for a in actors[:200]]))

        pgs = snap["placement_groups"]
        if isinstance(pgs, list) and pgs:
            parts.append(f"<h2>Placement groups ({len(pgs)})</h2>")
            parts.append(_table(
                ["id", "strategy", "bundles"],
                [[p.get("id", "")[:12], html.escape(str(p.get("strategy"))),
                  html.escape(json.dumps(p.get("bundles")))]
                 for p in pgs]))

        events = snap.get("events")
        if isinstance(events, list) and events:
            parts.append(f"<h2>Events ({len(events)} recent)</h2>")
            parts.append(_table(
                ["severity", "label", "message"],
                [[_pill(e.get("severity") not in ("ERROR", "FATAL"),
                        html.escape(str(e.get("severity", "?")))),
                  html.escape(str(e.get("label", ""))),
                  html.escape(str(e.get("message", "")))]
                 for e in events[-50:]]))

        objs = snap["objects"]
        if isinstance(objs, dict) and "error" not in objs:
            parts.append("<h2>Object store</h2>")
            parts.append(_table(["key", "value"],
                                [[html.escape(k), html.escape(str(v))]
                                 for k, v in objs.items()]))
        return _PAGE.format(body="".join(parts))

    def _collect_stacks(self, worker: Optional[str],
                        node_filter: Optional[str]) -> Dict[str, Any]:
        """Blocking concurrent fan-out to every node's worker_stacks."""
        from raytpu.util.stack_dump import collect_cluster_stacks

        return collect_cluster_stacks(self._worker_nodes(), worker=worker,
                                      node_filter=node_filter)

    def _collect_profile(self, worker: Optional[str],
                         node_filter: Optional[str],
                         duration_s: float, hz: float,
                         include_idle: bool) -> Dict[str, Any]:
        """Concurrent cluster-wide sampling profile (one duration_s
        total: every node samples its workers in parallel)."""
        from raytpu.util.stack_dump import fanout_node_call

        return fanout_node_call(
            self._worker_nodes(), "worker_profile", worker, duration_s,
            hz, include_idle, node_filter=node_filter,
            timeout=duration_s + 60.0)

    def _collect_memprofile(self, worker: Optional[str],
                            node_filter: Optional[str],
                            duration_s: float, trace_frames: int,
                            stop_after: bool) -> Dict[str, Any]:
        """Concurrent cluster-wide allocation profile (one shared
        window, same fan-out as _collect_profile)."""
        from raytpu.util.stack_dump import fanout_node_call

        return fanout_node_call(
            self._worker_nodes(), "worker_memory_profile", worker,
            duration_s, trace_frames, 40, stop_after,
            node_filter=node_filter, timeout=duration_s + 60.0)

    def _worker_nodes(self):
        import raytpu

        return [(n.get("NodeID", ""), n["Address"])
                for n in raytpu.nodes()
                if n.get("Alive")
                and n.get("Labels", {}).get("role") != "driver"]

    def _store_profile(self, mode: str, since_s: float,
                       recent_s: float) -> Optional[Dict[str, Any]]:
        """Continuous-profile store query against the head's
        ``profile_query`` RPC; None when not in cluster mode or the
        head is unreachable."""
        from raytpu.runtime import api as rt_api

        b = rt_api._backend
        if b is None or type(b).__name__ != "ClusterBackend":
            return None
        try:
            return b._head.call("profile_query", mode, since_s, 0.0,
                                recent_s)
        except Exception:
            return None

    def _cluster_prometheus(self) -> Optional[str]:
        """Cluster-aggregated exposition text from the head TSDB; None
        when not in cluster mode or the head is unreachable (callers
        fall back to the per-process registry)."""
        from raytpu.runtime import api as rt_api

        b = rt_api._backend
        if b is None or type(b).__name__ != "ClusterBackend":
            return None
        try:
            return b._head.call("metrics_prometheus")
        except Exception:
            return None

    _LOG_CHUNK = 1 << 20
    _LOG_MAX_BYTES = 8 << 20  # full-file reads cap here, flagged

    def _list_logs(self) -> Dict[str, Any]:
        from raytpu.util.stack_dump import fanout_node_call

        return fanout_node_call(self._worker_nodes(), "list_logs",
                                timeout=10.0)

    def _read_log(self, node_id: str, name: str,
                  tail: int = 0) -> Optional[str]:
        from raytpu.cluster.protocol import RpcClient

        for nid, addr in self._worker_nodes():
            if not nid.startswith(node_id):
                continue
            try:
                cli = RpcClient(addr)
                try:
                    if tail > 0:
                        # True tail: read from the END of the file (the
                        # listing has the size), not the first chunk.
                        size = 0
                        for e in cli.call("list_logs", timeout=10.0):
                            if e["name"] == name:
                                size = int(e["size"])
                        offset = max(0, size - self._LOG_CHUNK)
                        chunk = cli.call("read_log", name, offset,
                                         timeout=15.0)
                        if chunk is None:
                            return None
                        lines = chunk.decode("utf-8",
                                             "replace").splitlines()
                        if offset > 0 and lines:
                            lines = lines[1:]  # first line may be cut
                        return "\n".join(lines[-tail:])
                    parts = []
                    offset = 0
                    truncated = False
                    while True:
                        chunk = cli.call("read_log", name, offset,
                                         timeout=15.0)
                        if chunk is None:
                            return None if offset == 0 else "".join(parts)
                        parts.append(chunk.decode("utf-8", "replace"))
                        offset += len(chunk)
                        if len(chunk) < self._LOG_CHUNK:
                            break
                        if offset >= self._LOG_MAX_BYTES:
                            truncated = True
                            break
                    text = "".join(parts)
                    if truncated:
                        text += (f"\n... [truncated at {offset} bytes; "
                                 f"use ?tail=N or the raytpu logs CLI]\n")
                    return text
                finally:
                    cli.close()
            except Exception:
                return None
        return None

    # -- server ------------------------------------------------------------

    async def _start_async(self):
        from aiohttp import web

        async def index(request):
            return web.Response(text=self._render_summary(),
                                content_type="text/html")

        async def api_summary(request):
            return web.json_response(self._snapshot())

        async def api_section(request):
            snap = self._snapshot()
            key = request.match_info["section"]
            if key not in snap:
                return web.Response(status=404, text=f"no section {key}")
            return web.json_response({key: snap[key]})

        async def timeline(request):
            import raytpu

            events = raytpu.timeline()
            return web.Response(
                text=json.dumps(events),
                content_type="application/json",
                headers={"Content-Disposition":
                         "attachment; filename=timeline.json"})

        async def api_trace(request):
            """Cluster-wide chrome trace: fans out ``trace_dump`` through
            the connected backend (driver -> head -> nodes -> workers) and
            merges every process's span buffer into one timeline."""
            from raytpu.util.tracing import cluster_timeline

            loop = asyncio.get_running_loop()
            events = await loop.run_in_executor(None, cluster_timeline)
            return web.Response(
                text=json.dumps(events),
                content_type="application/json",
                headers={"Content-Disposition":
                         "attachment; filename=trace.json"})

        async def metrics(request):
            """Prometheus exposition. Default is the head TSDB's
            cluster-aggregated view — every process's shipped series
            behind one scrape target. ``?local=1`` keeps the legacy
            per-process prometheus_client registry."""
            if request.query.get("local") != "1":
                loop = asyncio.get_running_loop()
                text = await loop.run_in_executor(
                    None, self._cluster_prometheus)
                if text is not None:
                    return web.Response(text=text,
                                        content_type="text/plain")
            try:
                import prometheus_client

                text = prometheus_client.generate_latest().decode()
            except Exception:
                text = "# prometheus_client unavailable\n"
            return web.Response(text=text, content_type="text/plain")

        async def api_metrics_query(request):
            """Cluster-aggregated time series from the head TSDB.
            ?name= (required), ?agg=sum|max|min|avg|rate|p50..p99,
            ?since=<seconds>, ?step=<seconds>, ?tag.<key>=<val>."""
            from raytpu.state import api as state

            q = request.query
            name = q.get("name")
            if not name:
                return web.Response(status=400, text="name is required")
            try:
                since_s = float(q.get("since", 600.0))
                step = float(q["step"]) if q.get("step") else None
            except ValueError:
                return web.Response(status=400,
                                    text="since/step must be numbers")
            tags = {k[4:]: v for k, v in q.items()
                    if k.startswith("tag.")} or None
            loop = asyncio.get_running_loop()
            try:
                data = await loop.run_in_executor(
                    None, lambda: state.query_metrics(
                        name, tags=tags, agg=q.get("agg", "sum"),
                        since_s=since_s, step=step))
            except Exception as e:
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=503)
            if data is None:
                return web.Response(status=503, text="head unreachable")
            return web.json_response(data)

        async def api_metrics_series(request):
            """Every live (name, tags, kind) series the head TSDB holds;
            ?prefix= filters by metric-name prefix."""
            from raytpu.state import api as state

            prefix = request.query.get("prefix") or None
            loop = asyncio.get_running_loop()
            try:
                data = await loop.run_in_executor(
                    None, state.list_metric_series, prefix)
            except Exception as e:
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=503)
            if data is None:
                return web.Response(status=503, text="head unreachable")
            return web.json_response(data)

        async def logs_index(request):
            """Per-node log file listing (reference: the dashboard's log
            viewer over each node's session dir)."""
            loop = asyncio.get_running_loop()
            listing = await loop.run_in_executor(None, self._list_logs)
            rows = []
            for node_id, entries in sorted(listing.items()):
                if isinstance(entries, dict):  # error
                    rows.append([node_id[:12],
                                 html.escape(str(entries.get("error"))),
                                 ""])
                    continue
                for e in entries:
                    name = html.escape(e["name"])
                    link = (f'<a href="/logs/{node_id}/{name}">'
                            f'{name}</a>')
                    rows.append([node_id[:12], link, e["size"]])
            body = (f"<h2>Logs ({len(rows)} files)</h2>"
                    + _table(["node", "file", "bytes"], rows))
            return web.Response(text=_PAGE.format(body=body),
                                content_type="text/html")

        async def log_file(request):
            loop = asyncio.get_running_loop()
            node_id = request.match_info["node_id"]
            name = request.match_info["name"]
            try:
                tail = int(request.query.get("tail", 0) or 0)
            except ValueError:
                return web.Response(status=400,
                                    text="tail must be an integer")
            text = await loop.run_in_executor(
                None, self._read_log, node_id, name, tail)
            if text is None:
                return web.Response(status=404,
                                    text=f"no log {name} on {node_id}")
            return web.Response(text=text, content_type="text/plain")

        async def stacks(request):
            """Live worker stack dumps (reference: dashboard reporter's
            py-spy profiling endpoint). ?worker=<id prefix|daemon>,
            ?node=<node id prefix> narrow the dump."""
            loop = asyncio.get_running_loop()
            worker = request.query.get("worker") or None
            node_filter = request.query.get("node") or None
            result = await loop.run_in_executor(
                None, self._collect_stacks, worker, node_filter)
            return web.json_response(result)

        async def profile(request):
            """On-demand CPU flamegraph of live workers (reference:
            profile_manager.py py-spy endpoint). Query params:
            ?worker=<id prefix|daemon>, ?node=<id prefix>,
            ?duration=<s, default 2>, ?hz=<default 50>,
            ?idle=1 (keep parked threads), ?format=svg|json|collapsed.
            """
            from raytpu.util.profiler import (merge_collapsed,
                                              flamegraph_svg,
                                              to_collapsed_text)

            loop = asyncio.get_running_loop()
            worker = request.query.get("worker") or None
            node_filter = request.query.get("node") or None
            try:
                duration = float(request.query.get("duration", 2.0))
                hz = float(request.query.get("hz", 50.0))
            except ValueError:
                return web.Response(status=400,
                                    text="duration/hz must be numbers")
            include_idle = request.query.get("idle", "0") == "1"
            fmt = request.query.get("format", "svg")
            result = await loop.run_in_executor(
                None, self._collect_profile, worker, node_filter,
                duration, hz, include_idle)
            if fmt == "json":
                return web.json_response(result)
            merged = merge_collapsed(
                w.get("profile", {}).get("collapsed", {})
                for node in result.values() if isinstance(node, dict)
                for w in node.values() if isinstance(w, dict))
            if fmt == "collapsed":
                return web.Response(
                    text=to_collapsed_text(merged),
                    content_type="text/plain",
                    headers={"Content-Disposition":
                             "attachment; filename=profile.collapsed"})
            n_workers = sum(
                1 for node in result.values() if isinstance(node, dict)
                for w in node.values()
                if isinstance(w, dict) and "profile" in w)
            svg = flamegraph_svg(
                merged, title=f"{n_workers} process(es), {duration:g}s "
                              f"@ {hz:g} Hz"
                              + (" (idle included)" if include_idle
                                 else ""))
            return web.Response(text=svg, content_type="image/svg+xml")

        async def memprofile(request):
            """On-demand allocation memory flamegraph of live workers
            (reference: profile_manager.py memray endpoint). Query:
            ?worker=<id prefix|daemon>, ?node=<id prefix>,
            ?duration=<s, default 2>, ?frames=<traceback depth, 16>,
            ?stop=1 (turn tracing off after), ?format=svg|json|table.
            """
            from raytpu.util.memprofile import top_table
            from raytpu.util.profiler import (flamegraph_svg,
                                              merge_collapsed)

            loop = asyncio.get_running_loop()
            worker = request.query.get("worker") or None
            node_filter = request.query.get("node") or None
            try:
                duration = float(request.query.get("duration", 2.0))
                frames = int(request.query.get("frames", 16))
            except ValueError:
                return web.Response(
                    status=400, text="duration/frames must be numbers")
            stop_after = request.query.get("stop", "0") == "1"
            fmt = request.query.get("format", "svg")
            result = await loop.run_in_executor(
                None, self._collect_memprofile, worker, node_filter,
                duration, frames, stop_after)
            worker_mems = [
                w for node in result.values() if isinstance(node, dict)
                for w in node.values()
                if isinstance(w, dict) and "memory" in w]
            if fmt == "json":
                return web.json_response(result)
            if fmt == "table":
                text = "\n\n".join(top_table(w["memory"])
                                   for w in worker_mems)
                return web.Response(text=text or "no profiles",
                                    content_type="text/plain")
            merged = merge_collapsed(
                w["memory"].get("collapsed", {}) for w in worker_mems)
            total = sum(w["memory"].get("total_kb", 0)
                        for w in worker_mems)
            svg = flamegraph_svg(
                merged, title=f"live python allocations — "
                              f"{len(worker_mems)} process(es), "
                              f"{total:,} KiB traced (weights = KiB)")
            return web.Response(text=svg, content_type="image/svg+xml")

        async def api_profile(request):
            """Continuous-profile store view (the head's ProfileStore,
            fed by every process while RAYTPU_PROFILE_CONTINUOUS=1 —
            no on-demand sampling). Query params: ?mode=merged|diff,
            ?since=<s, merged window>, ?recent=<s, diff window>,
            ?format=json|svg|collapsed."""
            from raytpu.util.profiler import (flamegraph_svg,
                                              to_collapsed_text)

            q = request.query
            mode = q.get("mode", "merged")
            if mode not in ("merged", "diff"):
                return web.Response(status=400,
                                    text="mode must be merged|diff")
            try:
                since_s = float(q.get("since", 600.0))
                recent_s = float(q.get("recent", 120.0))
            except ValueError:
                return web.Response(
                    status=400, text="since/recent must be numbers")
            loop = asyncio.get_running_loop()
            data = await loop.run_in_executor(
                None, self._store_profile, mode, since_s, recent_s)
            if data is None:
                return web.Response(
                    status=503,
                    text="profile store unavailable (not cluster mode "
                         "or head unreachable)")
            fmt = q.get("format", "json")
            if fmt == "json":
                return web.json_response(data)
            collapsed = (data.get("delta") if mode == "diff"
                         else data.get("collapsed")) or {}
            if fmt == "collapsed":
                return web.Response(
                    text=to_collapsed_text(collapsed),
                    content_type="text/plain",
                    headers={"Content-Disposition":
                             "attachment; filename=profile.collapsed"})
            if mode == "diff":
                title = (f"cluster profile diff — last {recent_s:g}s "
                         f"minus prior {recent_s:g}s")
            else:
                title = (f"cluster profile — last {since_s:g}s, "
                         f"{data.get('samples', 0)} samples, "
                         f"{len(data.get('procs') or [])} proc(s)")
            # SVG weights must be positive; a diff keeps what got hotter.
            pos = {k: v for k, v in collapsed.items() if v > 0}
            return web.Response(text=flamegraph_svg(pos, title=title),
                                content_type="image/svg+xml")

        async def api_state_list(request):
            """Flight-recorder state listings (reference: the state API
            REST endpoints over GcsTaskManager). ?state= ?node= ?name=
            filter; ?detail=1 attaches event timelines."""
            from raytpu.state import api as state

            kind = request.match_info["kind"]
            q = request.query
            detail = q.get("detail", "0") == "1"
            try:
                limit = int(q.get("limit", 100))
            except ValueError:
                return web.Response(status=400,
                                    text="limit must be an integer")
            loop = asyncio.get_running_loop()
            try:
                if kind == "tasks":
                    data = await loop.run_in_executor(
                        None, lambda: state.list_tasks(
                            state=q.get("state"), node=q.get("node"),
                            name=q.get("name"), detail=detail,
                            limit=limit))
                elif kind == "actors":
                    data = await loop.run_in_executor(
                        None, lambda: state.list_actors(
                            state=q.get("state"), node=q.get("node"),
                            name=q.get("name"), detail=detail))
                elif kind == "objects":
                    data = await loop.run_in_executor(
                        None, lambda: state.list_objects(detail=detail))
                elif kind == "nodes":
                    data = await loop.run_in_executor(
                        None, lambda: state.list_nodes(detail=detail))
                else:
                    return web.Response(status=404,
                                        text=f"unknown kind {kind!r}")
            except Exception as e:
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=503)
            return web.json_response(data)

        async def api_state_summary(request):
            from raytpu.state import api as state

            kind = request.match_info["kind"]
            if kind not in ("tasks", "actors"):
                return web.Response(status=404,
                                    text=f"no summary for {kind!r}")
            fn = (state.summary_tasks if kind == "tasks"
                  else state.summary_actors)
            loop = asyncio.get_running_loop()
            try:
                data = await loop.run_in_executor(None, fn)
            except Exception as e:
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=503)
            return web.json_response(data)

        async def api_state_timeline(request):
            from raytpu.state import api as state

            entity_id = request.match_info["entity_id"]
            kind = request.query.get("kind", "task")
            loop = asyncio.get_running_loop()
            try:
                data = await loop.run_in_executor(
                    None, state.get_timeline, entity_id, kind)
            except Exception as e:
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=503)
            if data is None:
                return web.Response(
                    status=404,
                    text=f"no recorded {kind} matching {entity_id!r}")
            return web.json_response(data)

        app = web.Application()
        app.router.add_get("/", index)
        app.router.add_get("/api/summary", api_summary)
        # /api/trace and /api/state/* must register before the
        # /api/{section} wildcard or the section handler would 404 them
        # as unknown snapshot keys.
        app.router.add_get("/api/trace", api_trace)
        app.router.add_get("/api/metrics/query", api_metrics_query)
        app.router.add_get("/api/metrics/series", api_metrics_series)
        app.router.add_get("/api/profile", api_profile)
        app.router.add_get("/api/state/summary/{kind}", api_state_summary)
        app.router.add_get("/api/state/timeline/{entity_id}",
                           api_state_timeline)
        app.router.add_get("/api/state/{kind}", api_state_list)
        app.router.add_get("/api/{section}", api_section)
        app.router.add_get("/timeline", timeline)
        app.router.add_get("/metrics", metrics)
        app.router.add_get("/stacks", stacks)
        app.router.add_get("/profile", profile)
        app.router.add_get("/memprofile", memprofile)
        app.router.add_get("/logs", logs_index)
        app.router.add_get("/logs/{node_id}/{name}", log_file)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self._host, self._port)
        await site.start()
        port = self._runner.addresses[0][1] if self._runner.addresses \
            else self._port
        self.url = f"http://{self._host}:{port}"

    def start(self) -> str:
        import asyncio

        started = threading.Event()
        holder: Dict[str, Any] = {}

        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            loop.run_until_complete(self._start_async())
            holder["loop"] = loop
            started.set()
            loop.run_forever()

        self._thread = threading.Thread(target=run, name="raytpu-dashboard",
                                        daemon=True)
        self._thread.start()
        if not started.wait(timeout=15):
            raise RuntimeError("dashboard failed to start")
        self._loop = holder["loop"]
        return self.url

    def stop(self) -> None:
        import asyncio

        loop = getattr(self, "_loop", None)
        if loop is None:
            return

        async def _shutdown():
            if self._runner is not None:
                await self._runner.cleanup()
            loop.stop()

        asyncio.run_coroutine_threadsafe(_shutdown(), loop)
        if self._thread is not None:
            self._thread.join(timeout=10)
