"""Tenant identity propagation.

Reference analogue: Ray's multi-tenancy story is job-granular (one GCS
per cluster, per-job workers); large fleets layer *logical tenants* on
top — a namespace that quotas, fair-queueing, and billing key off.
raytpu makes the tenant a first-class ambient identity, carried exactly
like the PR-3 trace context:

- A driver (or any process) declares its tenant via the
  ``RAYTPU_TENANT`` env var, or scopes one dynamically with
  :func:`tenant_scope`.
- Every outbound RPC frame stamps the ambient tenant into the ``"tn"``
  envelope field (see :mod:`raytpu.cluster.protocol`), primitives-only
  so it survives the strict no-pickle wire.
- ``RpcServer._dispatch`` re-anchors ``"tn"`` into this module's
  contextvar per dispatch task, so head handlers (admission, quota
  checks) and node handlers (cross-language TaskSpec construction) see
  the *caller's* tenant without any parameter threading.
- :class:`~raytpu.runtime.task_spec.TaskSpec` carries ``tenant`` /
  ``priority`` / ``preemptible`` as appended wire-schema-safe fields;
  construction sites stamp them from here (lint rule RTP018 enforces
  that no seam forgets).

Cost model mirrors :mod:`raytpu.util.tracing`: with no tenant declared
anywhere, :func:`current_tenant` is one contextvar read plus one module
string read, and frames carry no extra field. The scheduler-side
semantics (quotas, weighted fair queueing, preemption, shedding) live in
``cluster/head.py`` behind the ``RAYTPU_TENANTS`` master switch.
"""

from __future__ import annotations

import contextvars
import os
from contextlib import contextmanager
from typing import Any, Optional

ENV_VAR = "RAYTPU_TENANT"

# The accounting bucket for traffic that declares no tenant at all.
# With RAYTPU_TENANTS=1 the head books untenanted work here so system
# traffic and legacy drivers still fall under *some* quota row.
DEFAULT_TENANT = "default"

# Process-level default, read once at import (workers and cluster
# daemons inherit os.environ, the failpoints/tracing arming pattern).
_env_default = os.environ.get(ENV_VAR, "") or ""

_current: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("raytpu_tenant", default=None)


def current_tenant() -> str:
    """The ambient tenant identity: innermost :func:`tenant_scope` or
    re-anchored frame value, else the process ``RAYTPU_TENANT`` default,
    else ``""`` (untenanted)."""
    t = _current.get()
    if t is not None:
        return t
    return _env_default


def set_current_tenant(tenant: Optional[str]):
    """Anchor ``tenant`` as the ambient identity; returns a reset token
    (``RpcServer._dispatch`` re-anchors per dispatch task with this)."""
    return _current.set(tenant)


def reset_current_tenant(token) -> None:
    _current.reset(token)


def set_process_tenant(tenant: str, env: bool = False) -> None:
    """Set the process-level default tenant. ``env=True`` additionally
    exports it so subprocesses spawned afterwards inherit it (the
    ``cfg(env=True)`` pattern from failpoints/tracing)."""
    global _env_default
    _env_default = str(tenant or "")
    if env:
        if _env_default:
            os.environ[ENV_VAR] = _env_default
        else:
            os.environ.pop(ENV_VAR, None)


@contextmanager
def tenant_scope(tenant: str):
    """Scope a tenant identity over a block of driver code::

        with tenancy.tenant_scope("team-interactive"):
            ref = f.remote()          # spec + frames carry the tenant
    """
    token = _current.set(str(tenant))
    try:
        yield
    finally:
        _current.reset(token)


def to_wire() -> Optional[str]:
    """The ``"tn"`` frame stamp: a plain str (strict-wire primitive), or
    None when no tenant is ambient (the field is then omitted — the
    untenanted wire is byte-identical to the pre-tenancy wire)."""
    t = current_tenant()
    return t or None


def from_wire(value: Any) -> Optional[str]:
    """Validate an inbound ``"tn"`` field (untrusted peer bytes)."""
    if isinstance(value, str) and value:
        return value
    return None
