"""Task-event flight recorder: lifecycle transitions as structured events.

Reference analogue (SURVEY §1): the GCS task-event store
(``GcsTaskManager``, ``src/ray/gcs/gcs_server/gcs_task_manager.cc``) —
every task/actor/object/node lifecycle transition is recorded as a
compact structured event, buffered per-process, batch-shipped to the
head, and queried through ``ray list tasks`` / ``ray summary``. PR 3's
tracing answers *where the time went*; this module answers *what
happened to my job* — the complementary lifecycle record a dead cluster
is debugged from.

Model:

- :func:`emit` appends one event (primitives only — the batch must
  encode on strict ``allow_pickle=False`` wire surfaces) to a bounded
  per-process ring buffer. A full ring evicts the OLDEST event and
  bumps a monotonic ``dropped`` counter: the hot path never blocks and
  the newest history always survives.
- Shippers (node heartbeat loop, worker post-task notify) call
  :func:`drain` and forward the batch to the head piggybacked on
  traffic that already flows; delivery failure calls :func:`requeue`.
- The head folds batches into a :class:`TaskEventStore` — bounded
  per-kind, FIFO-evicting, O(1) indexed by id and by state — which the
  state API, CLI and dashboard read.
- :func:`write_postmortem` snapshots the local ring + open breakers +
  recent operational events to the log dir, so the flight record
  outlives the process that crashed.

Cost model mirrors :mod:`raytpu.util.tracing` / failpoints: disabled,
an emission site is ONE module-flag check (sites guard with
``if task_events.enabled():``; :func:`emit` double-checks for safety).
Arming is inherited by child processes via ``RAYTPU_TASK_EVENTS``.

Events cross-link to PR-3 traces: when a sampled
:class:`~raytpu.util.tracing.TraceContext` is ambient at emission time,
its trace id rides the event, so ``raytpu state timeline <task>`` points
straight into the chrome-trace for the same attempt.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

ENV_VAR = "RAYTPU_TASK_EVENTS"
RING_ENV_VAR = "RAYTPU_TASK_EVENTS_RING"
REQUEST_ENV_VAR = "RAYTPU_REQUEST_EVENTS"


def _env_truthy(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0", "false", "False")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class TaskTransition:
    """Every lifecycle state the recorder knows. The AST lint in
    tests/test_task_events.py asserts each member is emitted somewhere
    under ``raytpu/`` — a new state cannot be added without wiring its
    instrumentation."""

    # task lifecycle
    SUBMITTED = "SUBMITTED"          # driver/backend accepted the spec
    PENDING_SCHED = "PENDING_SCHED"  # waiting for a feasible node
    SCHEDULED = "SCHEDULED"          # head picked a node
    LEASED = "LEASED"                # node leased a worker process
    RUNNING = "RUNNING"              # worker entered user code
    FINISHED = "FINISHED"            # terminal success
    FAILED = "FAILED"                # attempt failed (may retry)
    RETRIED = "RETRIED"              # a new attempt was queued
    # actor lifecycle
    CREATED = "CREATED"
    RESTARTING = "RESTARTING"
    RESTARTED = "RESTARTED"
    DEAD = "DEAD"
    # object lifecycle
    PUT = "PUT"                      # became local in some store
    TRANSFERRED = "TRANSFERRED"      # crossed nodes (push or pull)
    # node lifecycle
    NODE_ADDED = "NODE_ADDED"
    NODE_DIED = "NODE_DIED"

    ALL: Tuple[str, ...] = (
        SUBMITTED, PENDING_SCHED, SCHEDULED, LEASED, RUNNING, FINISHED,
        FAILED, RETRIED, CREATED, RESTARTING, RESTARTED, DEAD, PUT,
        TRANSFERRED, NODE_ADDED, NODE_DIED,
    )


class RequestTransition:
    """Serving-plane request lifecycle. Same closed-vocabulary contract
    as :class:`TaskTransition`: lint rule RTP021 asserts every member is
    emitted somewhere under ``raytpu/`` — a state nobody emits makes
    ``raytpu serve requests --state X`` silently empty."""

    RECEIVED = "RECEIVED"            # handle/router accepted the call
    ROUTED = "ROUTED"                # router picked a replica
    QUEUED = "QUEUED"                # replica enqueued (pre-semaphore)
    ADMITTED = "ADMITTED"            # scheduler admitted to a batch
    PREFILL_START = "PREFILL_START"  # prompt compute dispatched
    PREFILL_END = "PREFILL_END"      # prompt KV materialised
    HANDOFF_START = "HANDOFF_START"  # pulling prefilled KV from a peer
    HANDOFF_END = "HANDOFF_END"      # pull done (data: pages, fallback)
    FIRST_TOKEN = "FIRST_TOKEN"      # first output token sampled
    PREEMPTED = "PREEMPTED"          # evicted to recompute (KV freed)
    RESUMED = "RESUMED"              # re-admitted after preemption
    FINISHED = "FINISHED"            # terminal success (data: tokens_out)
    ABORTED = "ABORTED"              # consumer cancelled
    FAILED = "FAILED"                # stream died (error summary rides)

    ALL: Tuple[str, ...] = (
        RECEIVED, ROUTED, QUEUED, ADMITTED, PREFILL_START, PREFILL_END,
        HANDOFF_START, HANDOFF_END, FIRST_TOKEN, PREEMPTED, RESUMED,
        FINISHED, ABORTED, FAILED,
    )


KINDS = ("task", "actor", "object", "node", "request")

_RING = max(64, _env_int(RING_ENV_VAR, 8192))
_ring: "deque[dict]" = deque(maxlen=_RING)
_lock = threading.Lock()
_enabled = _env_truthy(ENV_VAR)
_request_enabled = _env_truthy(REQUEST_ENV_VAR)
_dropped_total = 0    # monotonic: events lost locally OR reported by
_dropped_shipped = 0  # an upstream emitter; shipped-watermark for drain
# [node_id, worker_id] — mutated in place (tracing._identity pattern) so
# events stamped after process setup carry their emitter.
_identity: List[str] = ["", ""]


def enabled() -> bool:
    return _enabled


def enable_task_events(env: bool = False,
                       ring_size: Optional[int] = None) -> None:
    """Arm the recorder. ``env=True`` exports ``RAYTPU_TASK_EVENTS`` so
    child processes — cluster daemons, pool workers — inherit the arming
    (failpoints' ``cfg(env=True)`` pattern). ``ring_size`` rebuilds the
    local ring (tests shrink it to force drops)."""
    global _enabled, _ring
    if ring_size is not None:
        with _lock:
            _ring = deque(_ring, maxlen=max(1, int(ring_size)))
    _enabled = True
    if env:
        os.environ[ENV_VAR] = "1"
        if ring_size is not None:
            os.environ[RING_ENV_VAR] = str(int(ring_size))


def disable_task_events(env: bool = False) -> None:
    global _enabled
    _enabled = False
    if env:
        os.environ.pop(ENV_VAR, None)
        os.environ.pop(RING_ENV_VAR, None)


def request_events_enabled() -> bool:
    """The request-timeline flag — independent of :func:`enabled` so a
    serving cluster records request waterfalls without paying for the
    task/actor/object firehose (and vice versa)."""
    return _request_enabled


def enable_request_events(env: bool = False) -> None:
    """Arm request-lifecycle recording. ``env=True`` exports
    ``RAYTPU_REQUEST_EVENTS`` so spawned daemons/replicas inherit."""
    global _request_enabled
    _request_enabled = True
    if env:
        os.environ[REQUEST_ENV_VAR] = "1"


def disable_request_events(env: bool = False) -> None:
    global _request_enabled
    _request_enabled = False
    if env:
        os.environ.pop(REQUEST_ENV_VAR, None)


def ship_enabled() -> bool:
    """True when ANY event class is armed — the shipping seams (node
    heartbeat drain, worker post-task flush, head ingest) gate on this,
    not on :func:`enabled`, so request events reach the head even when
    the task firehose is off."""
    return _enabled or _request_enabled


def set_emitter_identity(node_id: str = "", worker_id: str = "") -> None:
    """Stamp this process's emitter ids onto every future event (set
    once at daemon/worker startup, like tracing.set_process_identity)."""
    if node_id:
        _identity[0] = str(node_id)
    if worker_id:
        _identity[1] = str(worker_id)


def emit(kind: str, entity_id: str, transition: str, *,
         name: Optional[str] = None, attempt: int = 0,
         error: Optional[str] = None,
         parent_task_id: Optional[str] = None,
         node_id: Optional[str] = None,
         worker_id: Optional[str] = None) -> None:
    """Record one lifecycle transition. Never blocks, never raises on
    the hot path; a full ring drops the oldest event and counts it."""
    global _dropped_total
    if not _enabled:
        return
    ev: Dict[str, Any] = {
        "kind": kind,
        "id": str(entity_id),
        "transition": transition,
        "ts": time.time(),
        "mono": time.monotonic(),
        "node_id": node_id if node_id is not None else _identity[0],
        "worker_id": worker_id if worker_id is not None else _identity[1],
        "attempt": int(attempt),
    }
    if name is not None:
        ev["name"] = str(name)
    if error is not None:
        # Summary only — full tracebacks live in logs, not the wire.
        ev["error"] = str(error)[:256]
    if parent_task_id is not None:
        ev["parent_task_id"] = str(parent_task_id)
    try:
        from raytpu.util import tracing

        tc = tracing.current_trace()
        if tc is not None and tc.sampled:
            ev["trace_id"] = tc.trace_id
    except Exception:
        pass
    with _lock:
        if len(_ring) == _ring.maxlen:
            _dropped_total += 1
        _ring.append(ev)


def emit_request(request_id: str, transition: str, *,
                 deployment: str = "", tenant: str = "",
                 data: Optional[Dict[str, Any]] = None,
                 error: Optional[str] = None, attempt: int = 0) -> None:
    """Record one request lifecycle transition (primitives only — the
    batch crosses strict ``allow_pickle=False`` wire surfaces). Same
    never-block contract as :func:`emit`; call sites guard with
    ``if task_events.request_events_enabled():`` (RTP021 enforces the
    one-flag-check budget) and :func:`emit_request` double-checks."""
    global _dropped_total
    if not _request_enabled:
        return
    ev: Dict[str, Any] = {
        "kind": "request",
        "id": str(request_id),
        "transition": transition,
        "ts": time.time(),
        "mono": time.monotonic(),
        "node_id": _identity[0],
        "worker_id": _identity[1],
        "attempt": int(attempt),
        "deployment": str(deployment or ""),
        "tenant": str(tenant or ""),
    }
    if data is not None:
        ev["data"] = data
    if error is not None:
        ev["error"] = str(error)[:256]
    try:
        from raytpu.util import tracing

        tc = tracing.current_trace()
        if tc is not None and tc.sampled:
            ev["trace_id"] = tc.trace_id
    except Exception:
        pass
    with _lock:
        if len(_ring) == _ring.maxlen:
            _dropped_total += 1
        _ring.append(ev)


def dropped_count() -> int:
    """Monotonic count of events lost before reaching a store: local
    ring evictions plus drops reported by upstream emitters via
    :func:`ingest`."""
    return _dropped_total


def get_events() -> List[dict]:
    with _lock:
        return list(_ring)


def clear() -> None:
    """Drop buffered events and reset drop accounting (test isolation)."""
    global _dropped_total, _dropped_shipped
    with _lock:
        _ring.clear()
        _dropped_total = 0
        _dropped_shipped = 0


def drain() -> Tuple[List[dict], int]:
    """Pop everything buffered for shipping. Returns ``(batch,
    dropped_delta)`` — the delta is the number of events lost since the
    last successful drain, so the head's drop accounting stays exact
    across repeated ships."""
    global _dropped_shipped
    with _lock:
        batch = list(_ring)
        _ring.clear()
        delta = _dropped_total - _dropped_shipped
        _dropped_shipped = _dropped_total
    return batch, delta


def requeue(batch: List[dict], dropped: int = 0) -> None:
    """Put a failed ship back at the FRONT of the ring (oldest-first
    order preserved). Overflow drops the oldest of the requeued batch —
    never newer events recorded meanwhile."""
    global _dropped_total, _dropped_shipped
    if not batch and not dropped:
        return
    with _lock:
        _dropped_shipped -= int(dropped)
        space = (_ring.maxlen or 0) - len(_ring)
        if len(batch) > space:
            _dropped_total += len(batch) - space
            batch = batch[len(batch) - space:]
        _ring.extendleft(reversed(batch))


def ingest(batch: List[dict], dropped: int = 0) -> None:
    """Fold a downstream emitter's shipped batch into the LOCAL ring
    (a node daemon relaying its workers' events toward the head).
    Forwarded drop counts accumulate into this process's total so the
    head eventually sees every loss."""
    global _dropped_total
    if not batch and not dropped:
        return
    with _lock:
        _dropped_total += int(dropped)
        for ev in batch:
            if isinstance(ev, dict):
                if len(_ring) == _ring.maxlen:
                    _dropped_total += 1
                _ring.append(ev)


# -- head-side store ----------------------------------------------------------


class TaskEventStore:
    """Bounded per-kind event store: FIFO-evicting OrderedDicts keyed by
    entity id, with a by-state index kept in lockstep (reference:
    ``GcsTaskManager::GcsTaskManagerStorage`` — bounded task storage
    with job/state indexes, oldest-first eviction).

    One entity record folds its event stream: current ``state`` is the
    latest transition, ``events`` keeps the (bounded) timeline, and
    summary fields (name, node, attempt, error, trace id) are overlaid
    as events arrive, so a list query never walks event lists."""

    def __init__(self, per_kind: int = 4096, events_per_entity: int = 256):
        self._per_kind = max(16, int(per_kind))
        self._events_per_entity = max(8, int(events_per_entity))
        self._lock = threading.Lock()
        self._entities: Dict[str, "OrderedDict[str, dict]"] = {
            k: OrderedDict() for k in KINDS}
        self._by_state: Dict[str, Dict[str, set]] = {k: {} for k in KINDS}
        self._evicted = 0
        self._dropped_reported = 0

    # -- writes --------------------------------------------------------------

    def add_batch(self, events: List[dict], dropped: int = 0) -> None:
        with self._lock:
            self._dropped_reported += int(dropped)
            for ev in events or ():
                if not isinstance(ev, dict):
                    continue
                kind = ev.get("kind")
                eid = ev.get("id")
                transition = ev.get("transition")
                if kind not in self._entities or not eid or not transition:
                    continue
                self._add_locked(kind, str(eid), transition, ev)

    def _add_locked(self, kind: str, eid: str, transition: str,
                    ev: dict) -> None:
        table = self._entities[kind]
        index = self._by_state[kind]
        rec = table.get(eid)
        if rec is None:
            while len(table) >= self._per_kind:
                old_id, old = table.popitem(last=False)
                ids = index.get(old["state"])
                if ids is not None:
                    ids.discard(old_id)
                    if not ids:
                        index.pop(old["state"], None)
                self._evicted += 1
            rec = {"kind": kind, "id": eid, "state": transition,
                   "name": None, "node_id": None, "worker_id": None,
                   "attempt": 0, "error": None, "trace_id": None,
                   "parent_task_id": None, "first_ts": ev.get("ts"),
                   "last_ts": ev.get("ts"), "_state_ts": ev.get("ts"),
                   "events": []}
            if kind == "request":
                # Serving-plane attribution rides the record so list
                # queries filter by deployment/tenant without walking
                # event lists. Other kinds keep their existing shape.
                rec["deployment"] = None
                rec["tenant"] = None
            table[eid] = rec
            index.setdefault(transition, set()).add(eid)
        else:
            # Batches from different processes arrive out of order (the
            # driver's heartbeat may land after the worker's): the state
            # overlay follows event wall time, never arrival order — else
            # a fast task sits forever at SUBMITTED because the driver's
            # beat clobbered the worker's FINISHED.
            ev_ts = ev.get("ts") or 0.0
            if ev_ts >= (rec["_state_ts"] or 0.0):
                if rec["state"] != transition:
                    ids = index.get(rec["state"])
                    if ids is not None:
                        ids.discard(eid)
                        if not ids:
                            index.pop(rec["state"], None)
                    index.setdefault(transition, set()).add(eid)
                rec["state"] = transition
                rec["_state_ts"] = ev_ts
        ts = ev.get("ts")
        if ts is not None:
            rec["last_ts"] = max(rec["last_ts"] or ts, ts)
            rec["first_ts"] = min(rec["first_ts"] or ts, ts)
        if ev.get("name"):
            rec["name"] = ev["name"]
        if kind == "request":
            if ev.get("deployment"):
                rec["deployment"] = ev["deployment"]
            if ev.get("tenant"):
                rec["tenant"] = ev["tenant"]
        if ev.get("node_id"):
            rec["node_id"] = ev["node_id"]
        if ev.get("worker_id"):
            rec["worker_id"] = ev["worker_id"]
        if ev.get("trace_id"):
            rec["trace_id"] = ev["trace_id"]
        if ev.get("parent_task_id"):
            rec["parent_task_id"] = ev["parent_task_id"]
        if ev.get("error") is not None:
            rec["error"] = ev["error"]
        rec["attempt"] = max(rec["attempt"], int(ev.get("attempt") or 0))
        evs = rec["events"]
        if len(evs) >= self._events_per_entity:
            evs.pop(0)
        evs.append(ev)

    # -- reads ---------------------------------------------------------------

    @staticmethod
    def _strip(rec: dict, detail: bool) -> dict:
        out = {k: v for k, v in rec.items()
               if k != "events" and not k.startswith("_")}
        out["num_events"] = len(rec["events"])
        if detail:
            out["events"] = sorted(rec["events"],
                                   key=lambda e: e.get("ts") or 0.0)
        return out

    def get(self, kind: str, entity_id: str) -> Optional[dict]:
        """Exact-id lookup, falling back to a unique hex prefix (CLI
        users paste truncated ids)."""
        with self._lock:
            table = self._entities.get(kind)
            if table is None:
                return None
            rec = table.get(entity_id)
            if rec is None and entity_id:
                matches = [r for i, r in table.items()
                           if i.startswith(entity_id)]
                if len(matches) == 1:
                    rec = matches[0]
            return self._strip(rec, detail=True) if rec else None

    def list(self, kind: str, state: Optional[str] = None,
             node: Optional[str] = None, name: Optional[str] = None,
             limit: int = 100, detail: bool = False) -> List[dict]:
        with self._lock:
            table = self._entities.get(kind)
            if table is None:
                return []
            if state:
                ids = self._by_state[kind].get(state.upper(), set())
                recs = [table[i] for i in ids if i in table]
            else:
                recs = list(table.values())
            out = []
            for rec in recs:
                if node and not str(rec.get("node_id") or
                                    "").startswith(node):
                    continue
                if name and name not in str(rec.get("name") or ""):
                    continue
                out.append(self._strip(rec, detail))
            out.sort(key=lambda r: r.get("last_ts") or 0.0, reverse=True)
            return out[:max(0, int(limit))] if limit else out

    def summary(self, kind: str) -> Dict[str, Any]:
        """Counts by state × name plus queue→run latency percentiles
        (wall-ts delta SUBMITTED → RUNNING per entity) — the ``ray
        summary tasks`` shape."""
        with self._lock:
            table = self._entities.get(kind, {})
            by_state: Dict[str, Dict[str, int]] = {}
            latencies: List[float] = []
            for rec in table.values():
                nm = rec.get("name") or "<unknown>"
                row = by_state.setdefault(rec["state"], {})
                row[nm] = row.get(nm, 0) + 1
                sub = run = None
                for ev in rec["events"]:
                    t = ev.get("transition")
                    if t == TaskTransition.SUBMITTED and sub is None:
                        sub = ev.get("ts")
                    elif t == TaskTransition.RUNNING and run is None:
                        run = ev.get("ts")
                if sub is not None and run is not None and run >= sub:
                    latencies.append(run - sub)
        out: Dict[str, Any] = {
            "kind": kind,
            "total": sum(sum(r.values()) for r in by_state.values()),
            "by_state": {s: dict(sorted(r.items())) for s, r in
                         sorted(by_state.items())},
        }
        if latencies:
            latencies.sort()

            def pct(p: float) -> float:
                i = min(len(latencies) - 1,
                        int(p * (len(latencies) - 1) + 0.5))
                return round(latencies[i], 6)

            out["queue_to_run_latency_s"] = {
                "count": len(latencies), "p50": pct(0.50),
                "p95": pct(0.95), "max": round(latencies[-1], 6)}
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entities": {k: len(t) for k, t in self._entities.items()},
                "evicted": self._evicted,
                "dropped_reported": self._dropped_reported,
            }


# -- post-mortem --------------------------------------------------------------

_POSTMORTEM_MIN_INTERVAL_S = 30.0
_postmortem_lock = threading.Lock()
_last_postmortem = [0.0]


def write_postmortem(log_dir: str, reason: str,
                     last_n: int = 2000) -> Optional[str]:
    """Dump the flight record to ``log_dir`` as one JSON file: last N
    local events + drop counters + open circuit breakers + recent
    operational events (:mod:`raytpu.util.events` incl. its own
    ``dropped_count``). Rate-limited per process; never raises — a
    post-mortem writer that crashes the crashing process helps no one.
    Returns the written path, or None when skipped/failed."""
    try:
        now = time.monotonic()
        with _postmortem_lock:
            if now - _last_postmortem[0] < _POSTMORTEM_MIN_INTERVAL_S:
                return None
            _last_postmortem[0] = now
        payload: Dict[str, Any] = {
            "reason": str(reason),
            "wall_time": time.time(),
            "pid": os.getpid(),
            "identity": list(_identity),
            "task_events": get_events()[-int(last_n):],
            "task_events_dropped": dropped_count(),
        }
        try:
            from raytpu.util import resilience

            payload["breakers"] = resilience.breaker_states()
        except Exception:
            payload["breakers"] = {}
        try:
            from raytpu.util import events as _events

            payload["recent_events"] = _events.recent_events()[-200:]
            payload["events_dropped"] = _events.dropped_count()
        except Exception:
            payload["recent_events"] = []
        try:
            from raytpu.util import profiler as _profiler

            if _profiler.profiling_enabled():
                frames = _profiler.prof_peek()
                payload["profile"] = {
                    "collapsed": _profiler.merge_collapsed(
                        [f[3] for f in frames]),
                    "frames": len(frames),
                    "samples": sum(int(f[4]) for f in frames),
                }
        except Exception:
            pass
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(
            log_dir, f"postmortem_{os.getpid()}_{int(time.time())}.json")
        with open(path, "w") as f:
            json.dump(payload, f, default=str)
        return path
    except Exception:
        return None
