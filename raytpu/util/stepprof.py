"""Step-level chip attribution: live MFU, step-time distributions, and
memory high-water gauges in the cluster TSDB.

The reference's train/serve dashboards read throughput from offline
bench JSONs; the ROADMAP's 40%+ MFU target needs a LIVE measurement.
This module derives per-step FLOPs from the jit ``cost_analysis`` at
compile time (cached per shape bucket — the lowering already happened,
so the question costs one AOT cache hit per bucket, never per step) and
divides by the chip's peak to emit ``raytpu_train_mfu`` /
``raytpu_infer_decode_mfu`` gauges plus step-time histograms that
``raytpu top`` and alert rules consume.

Every emission site is behind the ``profiling_enabled()`` flag at the
CALLER (lint rule RTP019) — this module never checks the flag itself,
so a hook pays exactly one boolean read when profiling is off.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

from raytpu.util.metrics import Gauge, Histogram

ENV_PEAK_FLOPS = "RAYTPU_CHIP_PEAK_FLOPS"

# Per-chip dense bf16 peak FLOP/s by device-kind substring (public TPU
# specs); first match wins. The CPU fallback makes MFU a *relative*
# utilization signal on dev boxes instead of an absent series.
_PEAK_BY_KIND = (
    ("v6e", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_FALLBACK_PEAK_FLOPS = 1e12

_STEP_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 2.5e-2, 5e-2, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def device_peak_flops() -> float:
    """Peak FLOP/s of one local chip: ``RAYTPU_CHIP_PEAK_FLOPS``
    override first, then the device-kind table, then the CPU fallback."""
    env = os.environ.get(ENV_PEAK_FLOPS, "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        kind = jax.local_devices()[0].device_kind.lower()
        for sub, peak in _PEAK_BY_KIND:
            if sub in kind:
                return peak
    except Exception:
        pass
    return _FALLBACK_PEAK_FLOPS


def cost_analysis_flops(jitted, *args, **kwargs) -> Optional[float]:
    """FLOPs for one call of ``jitted`` at these arg shapes via the AOT
    ``cost_analysis``; None when the backend doesn't report."""
    try:
        ca = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float((ca or {}).get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


class StepProfiler:
    """One per process and workload kind (``train`` / ``infer``)."""

    def __init__(self, kind: str = "train"):
        if kind == "train":
            self._mfu = Gauge("raytpu_train_mfu",
                              "model FLOPs utilization per train step")
            self._step = Histogram("raytpu_train_step_seconds",
                                   "train step wall time",
                                   boundaries=_STEP_BUCKETS)
        elif kind == "infer":
            self._mfu = Gauge("raytpu_infer_decode_mfu",
                              "model FLOPs utilization per decode step")
            self._step = Histogram("raytpu_infer_step_seconds",
                                   "decode step wall time",
                                   boundaries=_STEP_BUCKETS)
        else:
            raise ValueError(f"unknown StepProfiler kind {kind!r}")
        self.kind = kind
        self._hbm_used = Gauge("raytpu_hbm_used_bytes",
                               "device memory in use",
                               tag_keys=("device",))
        self._hbm_peak = Gauge("raytpu_hbm_peak_bytes",
                               "device memory high-water mark",
                               tag_keys=("device",))
        self._flops: Dict[object, Optional[float]] = {}
        self._peak: Optional[float] = None
        self._last_mark: Optional[float] = None
        self._lock = threading.Lock()

    # -- FLOPs accounting --------------------------------------------------

    def ensure_flops(self, key, thunk: Callable[[], Optional[float]]
                     ) -> Optional[float]:
        """Per-bucket cached FLOPs: ``thunk`` (e.g. a
        :func:`cost_analysis_flops` closure) runs once per distinct
        ``key`` — compile-time work stays at compile frequency."""
        with self._lock:
            if key in self._flops:
                return self._flops[key]
        try:
            flops = thunk()
            flops = float(flops) if flops else None
        except Exception:
            flops = None
        with self._lock:
            self._flops[key] = flops
        return flops

    def peak_flops(self) -> float:
        if self._peak is None:
            self._peak = device_peak_flops()
        return self._peak

    # -- emission (callers guard with profiling_enabled(); RTP019) ---------

    def observe_step(self, dt_s: float, key=None,
                     flops: Optional[float] = None) -> None:
        """One step took ``dt_s`` seconds; emit the step-time histogram
        and, when per-step FLOPs are known (explicit or cached under
        ``key``), the MFU gauge."""
        dt_s = float(dt_s)
        if dt_s <= 0:
            return
        self._step.observe(dt_s)
        if flops is None and key is not None:
            with self._lock:
                flops = self._flops.get(key)
        if flops:
            self._mfu.set(min(1.0, float(flops) / dt_s /
                              self.peak_flops()))

    def mark(self) -> Optional[float]:
        """Interval timing for loops with no explicit step boundary
        (train ``session.report``): returns the seconds since the last
        mark, or None on the first call."""
        now = time.perf_counter()
        with self._lock:
            last, self._last_mark = self._last_mark, now
        return (now - last) if last is not None else None

    def observe_hbm(self) -> None:
        """Device-memory gauges from ``jax.local_devices()`` memory
        stats when the backend reports them (TPU/GPU; CPU reports
        nothing and this is a quiet no-op)."""
        try:
            import jax

            for d in jax.local_devices():
                stats = d.memory_stats() or {}
                used = stats.get("bytes_in_use")
                peak = stats.get("peak_bytes_in_use")
                tag = {"device": f"{d.device_kind}:{d.id}"}
                if used is not None:
                    self._hbm_used.set(float(used), tags=tag)
                if peak is not None:
                    self._hbm_peak.set(float(peak), tags=tag)
        except Exception:
            pass


_profilers: Dict[str, StepProfiler] = {}
_factory_lock = threading.Lock()


def step_profiler(kind: str = "train") -> StepProfiler:
    """Process-wide singleton per kind, so the engine and the train
    session never double-register metric series."""
    with _factory_lock:
        sp = _profilers.get(kind)
        if sp is None:
            sp = _profilers[kind] = StepProfiler(kind)
        return sp
