"""Cross-language demo actors.

Reference analogue: the counter classes the reference's cross-language
docs/tests invoke from C++/Java workers (``cpp/src/ray/test/``,
``doc/source/ray-core/cross-language.rst``). Non-Python clients create
these by descriptor — ``raytpu.util.xlang:Counter`` — and every method
sticks to wire-encodable values (ints/floats/strings/lists/dicts), the
contract for crossing the language boundary.
"""

from __future__ import annotations


class Counter:
    """Minimal stateful actor for cross-language smoke tests."""

    def __init__(self, start: int = 0):
        self.value = int(start)

    def inc(self, n: int = 1) -> int:
        self.value += int(n)
        return self.value

    def get(self) -> int:
        return self.value

    def echo(self, x):
        return x


class KVStore:
    """Dict-backed store: cross-language state sharing demo."""

    def __init__(self):
        self._d = {}

    def put(self, key: str, value) -> None:
        self._d[key] = value

    def get(self, key: str, default=None):
        return self._d.get(key, default)

    def keys(self) -> list:
        return sorted(self._d)
