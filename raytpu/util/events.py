"""Structured events — the operational event framework.

Reference analogue: ``src/ray/util/event.h:41`` (``RAY_EVENT`` macros:
severity + label + structured fields, written to per-process event files)
and ``dashboard/modules/event/`` (cluster-wide surfacing). Ours:
:func:`record_event` appends JSONL to a per-process event file (when a
log dir is configured) and hands the event to an optional reporter — in
cluster mode the node daemon's reporter forwards to the head, which
keeps a bounded ring queryable via the state API / dashboard.

Severities mirror the reference: DEBUG/INFO/WARNING/ERROR/FATAL.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR", "FATAL")

_lock = threading.Lock()
_buffer: Deque[Dict[str, Any]] = deque(maxlen=1000)
_file_path: Optional[str] = None
_reporter: Optional[Callable[[Dict[str, Any]], None]] = None
_dropped = 0  # monotonic: events evicted from the ring by overflow


def configure(log_dir: Optional[str] = None,
              reporter: Optional[Callable[[Dict[str, Any]], None]] = None
              ) -> None:
    """Set the per-process event sink (file under ``log_dir``) and an
    optional forwarder (node daemon -> head)."""
    global _file_path, _reporter
    with _lock:
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            _file_path = os.path.join(log_dir,
                                      f"events-{os.getpid()}.jsonl")
        if reporter is not None:
            _reporter = reporter


def record_event(severity: str, label: str, message: str,
                 **fields: Any) -> Dict[str, Any]:
    """Record one structured event (reference: ``RAY_EVENT(severity,
    label) << message``). Never raises."""
    severity = severity.upper()
    if severity not in SEVERITIES:
        severity = "INFO"
    event = {
        "timestamp": time.time(),
        "severity": severity,
        "label": label,
        "message": message,
        "pid": os.getpid(),
        **{k: v for k, v in fields.items() if _plain(v)},
    }
    global _dropped
    with _lock:
        if len(_buffer) == _buffer.maxlen:
            _dropped += 1  # oldest record falls off; newest survives
        _buffer.append(event)
        path = _file_path
        reporter = _reporter
    if path:
        try:
            with open(path, "a") as f:
                f.write(json.dumps(event) + "\n")
        except OSError:
            pass
    if reporter is not None:
        try:
            reporter(event)
        except Exception:
            pass
    return event


def _plain(v: Any) -> bool:
    return isinstance(v, (str, int, float, bool, type(None)))


def recent_events(severity: Optional[str] = None,
                  label: Optional[str] = None) -> List[Dict[str, Any]]:
    with _lock:
        events = list(_buffer)
    if severity:
        events = [e for e in events if e["severity"] == severity.upper()]
    if label:
        events = [e for e in events if e["label"] == label]
    return events


def dropped_count() -> int:
    """Monotonically increasing count of events lost to ring overflow
    (the overflow signal ``recent_events`` alone cannot give; included
    in post-mortem dumps so truncation is visible, not silent)."""
    return _dropped


def reset() -> None:
    global _file_path, _reporter, _dropped
    with _lock:
        _buffer.clear()
        _file_path = None
        _reporter = None
        _dropped = 0
