from raytpu.util.actor_pool import ActorPool
from raytpu.util.queue import Queue
from raytpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "ActorPool",
    "Queue",
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
]
