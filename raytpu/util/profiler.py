"""Sampling CPU profiler + flamegraph rendering.

Reference analogue: ``dashboard/modules/reporter/profile_manager.py`` —
the reference shells out to py-spy for on-demand CPU flamegraphs of any
live worker. py-spy isn't shippable in a zero-egress image, so the
equivalent capability is in-process: a background thread samples
``sys._current_frames()`` at a fixed rate for a bounded duration and
aggregates the samples into collapsed stacks (Brendan Gregg's
``root;child;leaf count`` format — exactly what flamegraph tooling
consumes). Every worker serves this over its ``profile`` RPC; the node
fans out; the dashboard renders the merged result as a self-contained
SVG flamegraph.

What in-process sampling cannot see (and py-spy can): native code that
holds the GIL without returning to the interpreter. Everything
Python-visible — including time *waiting* on locks/IO — is captured;
idle-looking leaf frames can be filtered with ``include_idle=False``.
"""

from __future__ import annotations

import hashlib
import html
import os
import sys
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

# Leaf code names that mean "this thread is parked, not burning CPU" —
# a heuristic (py-spy uses native-state instead), documented as such.
_IDLE_LEAVES = {
    "wait", "acquire", "select", "poll", "epoll", "accept", "recv",
    "recv_into", "read", "readline", "sleep", "get", "join",
    "_wait_for_tstate_lock", "wait_for", "run_forever", "_run_once",
    "select_poll", "flowcontrol",
}

# Frame labels cached per code object (function-level granularity:
# ``name (file:def-line)``). Formatting a label per frame per thread
# per sample is THE sampling cost — with a dozen threads it puts
# milliseconds of GIL stall into every sample — and def-line keys are
# also stabler across runs than instruction-pointer lines, so diff
# flamegraphs churn less. Code objects are interned for the process
# lifetime; the cache is bounded by the live code set.
_code_labels: Dict[object, str] = {}


def _code_label(code) -> str:
    label = _code_labels.get(code)
    if label is None:
        label = (f"{code.co_name} "
                 f"({os.path.basename(code.co_filename)}:"
                 f"{code.co_firstlineno})")
        _code_labels[code] = label
    return label


def sample_for(duration_s: float = 2.0, hz: float = 50.0,
               include_idle: bool = True) -> dict:
    """Sample this process's Python stacks for ``duration_s``.

    Returns ``{"collapsed": {stack: count}, "samples": N,
    "duration_s": ..., "hz": ..., "pid": ...}`` where each ``stack`` is
    ``thread-name;outermost (file:line);...;leaf (file:line)``.
    """
    duration_s = max(0.05, min(float(duration_s), 120.0))
    hz = max(1.0, min(float(hz), 500.0))
    interval = 1.0 / hz
    collapsed: Dict[str, int] = {}
    samples = 0
    me = threading.get_ident()
    # Thread names once per burst, not per sample (enumerate allocates
    # under a lock); a thread born mid-burst keys as ``thread-<tid>``.
    names = {t.ident: t.name for t in threading.enumerate()}
    deadline = time.monotonic() + duration_s
    while True:
        now = time.monotonic()
        if now >= deadline:
            break
        frames = sys._current_frames()
        for tid, frame in frames.items():
            if tid == me:
                continue  # never profile the profiler
            stack: List[str] = []
            f = frame
            while f is not None:
                stack.append(_code_label(f.f_code))
                f = f.f_back
            if not stack:
                continue
            if not include_idle:
                leaf_name = frame.f_code.co_name
                if leaf_name in _IDLE_LEAVES:
                    continue
            stack.reverse()  # root first
            key = ";".join([names.get(tid, f"thread-{tid}")] + stack)
            collapsed[key] = collapsed.get(key, 0) + 1
        samples += 1
        # Fixed-rate pacing; sampling cost eats into the sleep.
        time.sleep(max(0.0, interval - (time.monotonic() - now)))
    return {"collapsed": collapsed, "samples": samples,
            "duration_s": duration_s, "hz": hz, "pid": os.getpid()}


def fold_threads(collapsed: Dict[str, int]) -> Dict[str, int]:
    """Strip the leading thread-name segment and aggregate same-stack
    frames across threads, iterating sorted keys so the result is
    byte-identical across runs. Thread names carry unstable serials
    (``ThreadPoolExecutor-0_3``), so an unfolded merge makes every diff
    flamegraph churn on pool-thread identity instead of code."""
    out: Dict[str, int] = {}
    for key in sorted(collapsed or {}):
        folded = key.split(";", 1)[1] if ";" in key else key
        out[folded] = out.get(folded, 0) + int(collapsed[key])
    return {k: out[k] for k in sorted(out)}


def merge_collapsed(profiles, fold: bool = False) -> Dict[str, int]:
    """Merge several ``collapsed`` dicts (e.g. one per worker) with
    deterministic (sorted-key) aggregation; ``fold=True`` additionally
    folds same-stack frames across threads via :func:`fold_threads`."""
    out: Dict[str, int] = {}
    for p in profiles:
        src = fold_threads(p) if fold else (p or {})
        for k in sorted(src):
            out[k] = out.get(k, 0) + int(src[k])
    return {k: out[k] for k in sorted(out)}


def diff_collapsed(recent: Dict[str, int],
                   baseline: Dict[str, int]) -> Dict[str, int]:
    """Signed per-stack delta ``recent - baseline`` (zero rows elided,
    sorted keys). Positive = the stack grew; negative = it shrank."""
    out: Dict[str, int] = {}
    for k in sorted(set(recent or {}) | set(baseline or {})):
        d = int((recent or {}).get(k, 0)) - int((baseline or {}).get(k, 0))
        if d:
            out[k] = d
    return out


def to_collapsed_text(collapsed: Dict[str, int]) -> str:
    """The canonical one-line-per-stack text flamegraph.pl consumes."""
    return "\n".join(f"{k} {v}" for k, v in
                     sorted(collapsed.items())) + "\n"


# -- flamegraph rendering ------------------------------------------------

class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.children: Dict[str, "_Node"] = {}


def _build_tree(collapsed: Dict[str, int]) -> _Node:
    root = _Node("all")
    for stack, count in collapsed.items():
        root.value += count
        node = root
        for part in stack.split(";"):
            child = node.children.get(part)
            if child is None:
                child = node.children[part] = _Node(part)
            child.value += count
            node = child
    return root


def _color(name: str) -> str:
    """Deterministic warm color per frame (classic flamegraph look)."""
    h = hashlib.md5(name.encode()).digest()
    r = 205 + h[0] % 50
    g = 60 + h[1] % 130
    b = h[2] % 55
    return f"rgb({r},{g},{b})"


def flamegraph_svg(collapsed: Dict[str, int],
                   title: str = "CPU flamegraph",
                   width: int = 1200) -> str:
    """Self-contained SVG flamegraph (no JS required; hover shows the
    frame + sample share via native ``<title>`` tooltips)."""
    root = _build_tree(collapsed)
    row_h = 17
    min_w = 0.5  # px; narrower frames are dropped (invisible anyway)
    rects: List[str] = []
    max_depth = 0

    def layout(node: _Node, x: float, depth: int, scale: float):
        nonlocal max_depth
        max_depth = max(max_depth, depth)
        cx = x
        for name, child in sorted(node.children.items(),
                                  key=lambda kv: -kv[1].value):
            w = child.value * scale
            if w < min_w:
                cx += w
                continue
            y = depth * row_h
            pct = 100.0 * child.value / max(1, root.value)
            label = html.escape(name)
            rects.append(
                f'<g><title>{label} — {child.value} samples '
                f'({pct:.1f}%)</title>'
                f'<rect x="{cx:.2f}" y="{y}" width="{w:.2f}" '
                f'height="{row_h - 1}" fill="{_color(name)}" rx="1"/>'
                + (f'<text x="{cx + 3:.2f}" y="{y + 12}" '
                   f'font-size="11" font-family="monospace" '
                   f'clip-path="inset(0)">'
                   f'{label[:max(1, int(w / 7))]}</text>'
                   if w > 25 else "")
                + "</g>")
            layout(child, cx, depth + 1, scale)
            cx += w
    if root.value > 0:
        layout(root, 0.0, 0, width / root.value)
    height = (max_depth + 2) * row_h + 30
    header = (f'<text x="4" y="16" font-size="13" '
              f'font-family="sans-serif">{html.escape(title)} — '
              f'{root.value} samples</text>')
    body = "".join(f'<g transform="translate(0,24)">{r}</g>'
                   for r in rects)
    return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
            f'height="{height}" viewBox="0 0 {width} {height}" '
            f'style="background:#fff">{header}{body}</svg>')


def profile_to_svg(profile: dict, title: Optional[str] = None) -> str:
    return flamegraph_svg(profile.get("collapsed", {}),
                          title or f"pid {profile.get('pid', '?')}, "
                                   f"{profile.get('samples', 0)} samples "
                                   f"@ {profile.get('hz', 0):g} Hz")


# ---------------------------------------------------------------------------
# Continuous profiling: always-on duty-cycled sampling + cluster shipping.
#
# Mirrors the metrics-shipping contract exactly (util/metrics.py): a
# bounded per-process frame buffer, per-origin monotonic seq for
# idempotent re-ship, watermark drop accounting across drain/requeue,
# and a relay ``ingest`` so worker frames ride the node's heartbeat.
#
# Frame shape (strict-wire primitives only):
#   [proc_id, seq, ts, collapsed, samples, window_s]
# with ``collapsed`` a thread-folded {stack: count} dict already capped
# to the top RAYTPU_PROFILE_STACKS_MAX stacks (remainder under "(other)").
# ---------------------------------------------------------------------------

ENV_PROFILE = "RAYTPU_PROFILE_CONTINUOUS"
ENV_PROFILE_PERIOD = "RAYTPU_PROFILE_PERIOD_S"
ENV_PROFILE_WINDOW = "RAYTPU_PROFILE_WINDOW_S"
ENV_PROFILE_HZ = "RAYTPU_PROFILE_HZ"
ENV_PROFILE_BUFFER_MAX = "RAYTPU_PROFILE_BUFFER_MAX"
ENV_PROFILE_STACKS_MAX = "RAYTPU_PROFILE_STACKS_MAX"

# Duty cycle: one PROFILE_WINDOW_S sampling burst at PROFILE_HZ every
# PROFILE_PERIOD_S — ~1e-3 duty at the defaults, so the always-on cost
# is the burst amortized to noise (BENCH_r18 pins it < 3%).
_PROFILE_PERIOD_S = float(os.environ.get(ENV_PROFILE_PERIOD, "") or 10.0)
_PROFILE_WINDOW_S = float(os.environ.get(ENV_PROFILE_WINDOW, "") or 1.0)
_PROFILE_HZ = float(os.environ.get(ENV_PROFILE_HZ, "") or 25.0)
_PROF_BUFFER_MAX = int(os.environ.get(ENV_PROFILE_BUFFER_MAX, "") or 64)
_PROF_STACKS_MAX = int(os.environ.get(ENV_PROFILE_STACKS_MAX, "") or 200)

_profile_enabled = os.environ.get(ENV_PROFILE, "") in ("1", "true", "True")
_prof_lock = threading.Lock()
_prof_frames: Deque[list] = deque()
_prof_dropped_total = 0
_prof_dropped_shipped = 0  # watermark: drops already reported downstream
_prof_seq = 0


def profiling_enabled() -> bool:
    """THE flag check: every continuous-profiler emission site guards
    with exactly this call (lint rule RTP019), so the default-off mode
    costs one boolean read per site."""
    return _profile_enabled


def enable_profiling(env: bool = False) -> None:
    global _profile_enabled
    _profile_enabled = True
    if env:
        os.environ[ENV_PROFILE] = "1"


def disable_profiling(env: bool = False) -> None:
    global _profile_enabled
    _profile_enabled = False
    if env:
        os.environ[ENV_PROFILE] = "0"


def _cap_stacks(collapsed: Dict[str, int],
                max_stacks: int) -> Dict[str, int]:
    """Bound one snapshot to the hottest ``max_stacks`` stacks (ties
    broken by key, so the cap is deterministic); everything below the
    cut folds into ``(other)`` — totals stay exact."""
    if len(collapsed) <= max_stacks:
        return collapsed
    ranked = sorted(collapsed.items(), key=lambda kv: (-kv[1], kv[0]))
    out = dict(sorted(ranked[:max_stacks]))
    rest = sum(v for _, v in ranked[max_stacks:])
    if rest:
        out["(other)"] = out.get("(other)", 0) + rest
    return out


def prof_snapshot(window_s: Optional[float] = None,
                  hz: Optional[float] = None) -> bool:
    """Sample one duty-cycle window and enqueue a bounded, thread-folded
    frame. Returns True iff a frame was produced."""
    from raytpu.util.failpoints import DROP, failpoint
    if failpoint("profile.snapshot") is DROP:
        return False
    w = _PROFILE_WINDOW_S if window_s is None else float(window_s)
    h = _PROFILE_HZ if hz is None else float(hz)
    prof = sample_for(w, h, include_idle=True)
    collapsed = _cap_stacks(fold_threads(prof["collapsed"]),
                            _PROF_STACKS_MAX)
    if not collapsed:
        return False
    from raytpu.util import metrics as _metrics
    global _prof_seq, _prof_dropped_total
    with _prof_lock:
        _prof_seq += 1
        frame = [_metrics.shipper_identity(), _prof_seq, time.time(),
                 collapsed, int(prof["samples"]), w]
        if len(_prof_frames) >= _PROF_BUFFER_MAX:
            _prof_frames.popleft()
            _prof_dropped_total += 1
        _prof_frames.append(frame)
    return True


def prof_drain() -> Tuple[List[list], int]:
    """Take everything pending plus the not-yet-reported drop delta; on
    ship failure hand both back via :func:`prof_requeue` (the watermark
    arithmetic keeps drop counts exact across retries)."""
    global _prof_dropped_shipped
    with _prof_lock:
        frames = list(_prof_frames)
        _prof_frames.clear()
        dropped_delta = _prof_dropped_total - _prof_dropped_shipped
        _prof_dropped_shipped = _prof_dropped_total
    return frames, dropped_delta


def prof_requeue(frames: List[list], dropped: int = 0) -> None:
    """Put a failed ship back at the FRONT of the buffer (oldest-first
    order preserved); overflow drops the oldest of the requeued batch."""
    if not frames and not dropped:
        return
    global _prof_dropped_total, _prof_dropped_shipped
    with _prof_lock:
        _prof_dropped_shipped -= dropped
        space = _PROF_BUFFER_MAX - len(_prof_frames)
        if len(frames) > space:
            lost = len(frames) - max(space, 0)
            frames = frames[lost:]
            _prof_dropped_total += lost
        _prof_frames.extendleft(reversed(frames))


def prof_discard(frames: List[list], dropped: int = 0) -> None:
    """A drained batch was LOST in flight (e.g. the ``profile.ship``
    failpoint dropped it): fold the lost frames into the drop counter
    and re-owe the already-watermarked drop delta, so the next
    successful drain reports every loss exactly once."""
    global _prof_dropped_total, _prof_dropped_shipped
    with _prof_lock:
        _prof_dropped_total += len(frames or ())
        _prof_dropped_shipped -= int(dropped or 0)


def prof_ingest(frames: List[list], dropped: int = 0) -> None:
    """Relay path: a node daemon absorbs a worker's drained frames into
    its own buffer; they ride the next heartbeat to the head."""
    global _prof_dropped_total
    with _prof_lock:
        _prof_dropped_total += int(dropped or 0)
        for f in frames or ():
            if len(_prof_frames) >= _PROF_BUFFER_MAX:
                _prof_frames.popleft()
                _prof_dropped_total += 1
            _prof_frames.append(f)


def prof_pending() -> int:
    with _prof_lock:
        return len(_prof_frames)


def prof_peek() -> List[list]:
    """Non-destructive copy of the pending buffer (post-mortem dumps:
    a crashing process's unshipped tail is evidence, not inventory)."""
    with _prof_lock:
        return list(_prof_frames)


def reset_prof_shipping() -> None:
    """Test isolation: clear the buffer, counters, and seq."""
    global _prof_dropped_total, _prof_dropped_shipped, _prof_seq
    with _prof_lock:
        _prof_frames.clear()
        _prof_dropped_total = 0
        _prof_dropped_shipped = 0
        _prof_seq = 0


class ContinuousProfiler:
    """Duty-cycled background sampler: one short ``sample_for`` burst
    every ``period_s``, snapshotting into the shipping buffer. The
    thread exists only when started; with profiling disabled it idles
    on the flag check and samples nothing."""

    def __init__(self, period_s: Optional[float] = None,
                 window_s: Optional[float] = None,
                 hz: Optional[float] = None):
        self.period_s = _PROFILE_PERIOD_S if period_s is None \
            else float(period_s)
        self.window_s = _PROFILE_WINDOW_S if window_s is None \
            else float(window_s)
        self.hz = _PROFILE_HZ if hz is None else float(hz)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="raytpu-profiler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.window_s + 1.0)
        self._thread = None
        self._stop = threading.Event()

    def _run(self) -> None:
        wait = max(0.05, self.period_s - self.window_s)
        while not self._stop.wait(wait):
            if profiling_enabled():
                prof_snapshot(self.window_s, self.hz)


_continuous: List[Optional[ContinuousProfiler]] = [None]


def start_continuous(period_s: Optional[float] = None,
                     window_s: Optional[float] = None,
                     hz: Optional[float] = None) -> ContinuousProfiler:
    """Idempotent per process: head/node/worker entry points call this
    once (behind the flag) and share the singleton sampler."""
    with _prof_lock:
        cp = _continuous[0]
        if cp is None:
            cp = _continuous[0] = ContinuousProfiler(period_s, window_s, hz)
    cp.start()
    return cp


def stop_continuous() -> None:
    with _prof_lock:
        cp = _continuous[0]
        _continuous[0] = None
    if cp is not None:
        cp.stop()
