"""Sampling CPU profiler + flamegraph rendering.

Reference analogue: ``dashboard/modules/reporter/profile_manager.py`` —
the reference shells out to py-spy for on-demand CPU flamegraphs of any
live worker. py-spy isn't shippable in a zero-egress image, so the
equivalent capability is in-process: a background thread samples
``sys._current_frames()`` at a fixed rate for a bounded duration and
aggregates the samples into collapsed stacks (Brendan Gregg's
``root;child;leaf count`` format — exactly what flamegraph tooling
consumes). Every worker serves this over its ``profile`` RPC; the node
fans out; the dashboard renders the merged result as a self-contained
SVG flamegraph.

What in-process sampling cannot see (and py-spy can): native code that
holds the GIL without returning to the interpreter. Everything
Python-visible — including time *waiting* on locks/IO — is captured;
idle-looking leaf frames can be filtered with ``include_idle=False``.
"""

from __future__ import annotations

import hashlib
import html
import os
import sys
import threading
import time
from typing import Dict, List, Optional

# Leaf code names that mean "this thread is parked, not burning CPU" —
# a heuristic (py-spy uses native-state instead), documented as such.
_IDLE_LEAVES = {
    "wait", "acquire", "select", "poll", "epoll", "accept", "recv",
    "recv_into", "read", "readline", "sleep", "get", "join",
    "_wait_for_tstate_lock", "wait_for", "run_forever", "_run_once",
    "select_poll", "flowcontrol",
}


def sample_for(duration_s: float = 2.0, hz: float = 50.0,
               include_idle: bool = True) -> dict:
    """Sample this process's Python stacks for ``duration_s``.

    Returns ``{"collapsed": {stack: count}, "samples": N,
    "duration_s": ..., "hz": ..., "pid": ...}`` where each ``stack`` is
    ``thread-name;outermost (file:line);...;leaf (file:line)``.
    """
    duration_s = max(0.05, min(float(duration_s), 120.0))
    hz = max(1.0, min(float(hz), 500.0))
    interval = 1.0 / hz
    collapsed: Dict[str, int] = {}
    samples = 0
    me = threading.get_ident()
    deadline = time.monotonic() + duration_s
    while True:
        now = time.monotonic()
        if now >= deadline:
            break
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in frames.items():
            if tid == me:
                continue  # never profile the profiler
            stack: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} "
                             f"({os.path.basename(code.co_filename)}:"
                             f"{f.f_lineno})")
                f = f.f_back
            if not stack:
                continue
            if not include_idle:
                leaf_name = frame.f_code.co_name
                if leaf_name in _IDLE_LEAVES:
                    continue
            stack.reverse()  # root first
            key = ";".join([names.get(tid, f"thread-{tid}")] + stack)
            collapsed[key] = collapsed.get(key, 0) + 1
        samples += 1
        # Fixed-rate pacing; sampling cost eats into the sleep.
        time.sleep(max(0.0, interval - (time.monotonic() - now)))
    return {"collapsed": collapsed, "samples": samples,
            "duration_s": duration_s, "hz": hz, "pid": os.getpid()}


def merge_collapsed(profiles) -> Dict[str, int]:
    """Merge several ``collapsed`` dicts (e.g. one per worker)."""
    out: Dict[str, int] = {}
    for p in profiles:
        for k, v in (p or {}).items():
            out[k] = out.get(k, 0) + int(v)
    return out


def to_collapsed_text(collapsed: Dict[str, int]) -> str:
    """The canonical one-line-per-stack text flamegraph.pl consumes."""
    return "\n".join(f"{k} {v}" for k, v in
                     sorted(collapsed.items())) + "\n"


# -- flamegraph rendering ------------------------------------------------

class _Node:
    __slots__ = ("name", "value", "children")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self.children: Dict[str, "_Node"] = {}


def _build_tree(collapsed: Dict[str, int]) -> _Node:
    root = _Node("all")
    for stack, count in collapsed.items():
        root.value += count
        node = root
        for part in stack.split(";"):
            child = node.children.get(part)
            if child is None:
                child = node.children[part] = _Node(part)
            child.value += count
            node = child
    return root


def _color(name: str) -> str:
    """Deterministic warm color per frame (classic flamegraph look)."""
    h = hashlib.md5(name.encode()).digest()
    r = 205 + h[0] % 50
    g = 60 + h[1] % 130
    b = h[2] % 55
    return f"rgb({r},{g},{b})"


def flamegraph_svg(collapsed: Dict[str, int],
                   title: str = "CPU flamegraph",
                   width: int = 1200) -> str:
    """Self-contained SVG flamegraph (no JS required; hover shows the
    frame + sample share via native ``<title>`` tooltips)."""
    root = _build_tree(collapsed)
    row_h = 17
    min_w = 0.5  # px; narrower frames are dropped (invisible anyway)
    rects: List[str] = []
    max_depth = 0

    def layout(node: _Node, x: float, depth: int, scale: float):
        nonlocal max_depth
        max_depth = max(max_depth, depth)
        cx = x
        for name, child in sorted(node.children.items(),
                                  key=lambda kv: -kv[1].value):
            w = child.value * scale
            if w < min_w:
                cx += w
                continue
            y = depth * row_h
            pct = 100.0 * child.value / max(1, root.value)
            label = html.escape(name)
            rects.append(
                f'<g><title>{label} — {child.value} samples '
                f'({pct:.1f}%)</title>'
                f'<rect x="{cx:.2f}" y="{y}" width="{w:.2f}" '
                f'height="{row_h - 1}" fill="{_color(name)}" rx="1"/>'
                + (f'<text x="{cx + 3:.2f}" y="{y + 12}" '
                   f'font-size="11" font-family="monospace" '
                   f'clip-path="inset(0)">'
                   f'{label[:max(1, int(w / 7))]}</text>'
                   if w > 25 else "")
                + "</g>")
            layout(child, cx, depth + 1, scale)
            cx += w
    if root.value > 0:
        layout(root, 0.0, 0, width / root.value)
    height = (max_depth + 2) * row_h + 30
    header = (f'<text x="4" y="16" font-size="13" '
              f'font-family="sans-serif">{html.escape(title)} — '
              f'{root.value} samples</text>')
    body = "".join(f'<g transform="translate(0,24)">{r}</g>'
                   for r in rects)
    return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
            f'height="{height}" viewBox="0 0 {width} {height}" '
            f'style="background:#fff">{header}{body}</svg>')


def profile_to_svg(profile: dict, title: Optional[str] = None) -> str:
    return flamegraph_svg(profile.get("collapsed", {}),
                          title or f"pid {profile.get('pid', '?')}, "
                                   f"{profile.get('samples', 0)} samples "
                                   f"@ {profile.get('hz', 0):g} Hz")
