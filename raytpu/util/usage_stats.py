"""Opt-out usage stats — local collection only.

Reference analogue: ``python/ray/_private/usage/usage_lib.py`` — Ray
records cluster metadata and library usage and (unless
``RAY_USAGE_STATS_ENABLED=0``) reports it. Ours keeps the same shape with
a privacy-first default for this environment: collection is in-process,
the report is written to a local JSON file under the session temp dir,
and nothing ever leaves the machine (the reporter interface is pluggable
so an operator can point it at their own endpoint).

Env knobs: ``RAYTPU_USAGE_STATS_ENABLED`` (default "1" — local file
only), ``RAYTPU_USAGE_STATS_PATH`` (default: ``<tmp>/usage_stats.json``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

_lock = threading.Lock()
_features: Dict[str, int] = {}
_extra: Dict[str, Any] = {}


def enabled() -> bool:
    return os.environ.get("RAYTPU_USAGE_STATS_ENABLED", "1") != "0"


def record_library_usage(name: str) -> None:
    """Count a feature/library touch (reference:
    ``usage_lib.record_library_usage``). Cheap; safe to call per-init."""
    if not enabled():
        return
    with _lock:
        _features[name] = _features.get(name, 0) + 1


def record_extra(key: str, value: Any) -> None:
    if not enabled():
        return
    with _lock:
        _extra[key] = value


def _cluster_metadata() -> Dict[str, Any]:
    import platform

    from raytpu._version import __version__

    meta = {
        "raytpu_version": __version__,
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "timestamp": int(time.time()),
    }
    try:
        import jax

        meta["jax_version"] = jax.__version__
    except Exception:
        pass
    return meta


def report(path: Optional[str] = None) -> Optional[str]:
    """Write the usage report locally; returns the path (None when
    disabled). Called at shutdown by the runtime; never raises."""
    if not enabled():
        return None
    try:
        path = path or os.environ.get(
            "RAYTPU_USAGE_STATS_PATH",
            os.path.join(os.environ.get("TMPDIR", "/tmp"),
                         "raytpu_usage_stats.json"))
        with _lock:
            payload = {
                **_cluster_metadata(),
                "library_usages": dict(_features),
                "extra": dict(_extra),
            }
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        return path
    except Exception:
        return None


def reset() -> None:
    with _lock:
        _features.clear()
        _extra.clear()
