"""In-process stack dumps for live workers.

Reference analogue: ``dashboard/modules/reporter/profile_manager.py`` —
the reference shells out to py-spy to snapshot any worker's stacks from
the dashboard. py-spy isn't shippable here (zero-egress image), so the
equivalent capability is in-process: every worker's RPC loop serves a
``stack`` call that formats ``sys._current_frames()`` for all threads —
the same information py-spy's ``dump`` mode prints, without ptrace.
A wedged task thread doesn't block the dump (the RPC loop is a separate
thread); only a worker hard-hung in native code without releasing the
GIL is unsnapshotable, which ptrace-based py-spy can still see — dump
the pid with gdb there.
"""

from __future__ import annotations

import sys
import threading
import traceback


def fanout_node_call(nodes, method: str, *args,
                     node_filter=None, timeout: float = 30.0):
    """Issue one RPC to every node concurrently (a wedged node costs at
    most one ``timeout``, not one per node — wedged nodes are exactly
    what the debugging endpoints built on this exist for).

    ``nodes``: iterable of ``(node_id, address)``. Returns
    ``{node_id: result or {"error": ...}}``.
    """
    from concurrent.futures import ThreadPoolExecutor

    from raytpu.cluster.protocol import RpcClient

    targets = [(nid, addr) for nid, addr in nodes
               if not node_filter or nid.startswith(node_filter)]
    if not targets:
        return {}

    def one(target):
        nid, addr = target
        try:
            cli = RpcClient(addr)
            try:
                return nid, cli.call(method, *args, timeout=timeout)
            finally:
                cli.close()
        except Exception as e:
            return nid, {"error": f"{type(e).__name__}: {e}"}

    with ThreadPoolExecutor(
            max_workers=min(16, len(targets)),
            thread_name_prefix="raytpu-fanout") as ex:
        return dict(ex.map(one, targets))


def collect_cluster_stacks(nodes, worker=None, node_filter=None,
                           timeout: float = 30.0):
    """Concurrent cluster-wide ``worker_stacks`` (see fanout_node_call)."""
    return fanout_node_call(nodes, "worker_stacks", worker,
                            node_filter=node_filter, timeout=timeout)


def dump_all_threads(header: str = "") -> str:
    """Format every thread's current stack, py-spy-dump style."""
    frames = sys._current_frames()
    by_id = {t.ident: t for t in threading.enumerate()}
    parts = []
    if header:
        parts.append(header)
    for tid, frame in sorted(frames.items()):
        t = by_id.get(tid)
        name = t.name if t is not None else f"<unknown-{tid}>"
        flags = []
        if t is not None and t.daemon:
            flags.append("daemon")
        if t is threading.main_thread():
            flags.append("main")
        suffix = f" ({', '.join(flags)})" if flags else ""
        parts.append(
            f'Thread "{name}" tid={tid}{suffix}:\n'
            + "".join(traceback.format_stack(frame)).rstrip())
    return "\n\n".join(parts) + "\n"
