"""Typed error taxonomy for the resilience layer.

Reference analogue: gRPC status codes (``UNAVAILABLE`` vs
``DEADLINE_EXCEEDED`` vs ``FAILED_PRECONDITION``) and Ray's
``RpcError``/``GetTimeoutError`` split: retry decisions must key off
*types*, never off string-matching an exception message. Every
hand-rolled ``except ValueError: if "retry" in str(e)`` site in the
cluster layer migrates onto this module.

The taxonomy has two roots under :class:`~raytpu.core.errors.RayTpuError`:

- :class:`RetryableError` — transient; a :class:`~raytpu.util.resilience.
  RetryPolicy` may re-attempt the operation.
- :class:`FatalError` — re-attempting cannot help (budget exhausted,
  breaker open, precondition failed); policies re-raise immediately.

Errors raised by lower layers (``ConnectionError``, ``OSError``,
``TimeoutError``) predate the taxonomy; :func:`is_retryable` classifies
them so policies work over the whole exception population. Everything
here is wire-encodable by :mod:`raytpu.cluster.wire` (the ``raytpu``
module prefix is on the strict-surface allowlist), so typed errors
survive the hop back to a remote caller.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

from raytpu.core.errors import RayTpuError


class RetryableError(RayTpuError):
    """Transient failure: the operation may succeed if re-attempted."""


class FatalError(RayTpuError):
    """Permanent failure: retrying cannot change the outcome."""


class NodeVanishedError(RetryableError):
    """A node selected by the scheduler disappeared before the operation
    reached it (raced with failure detection). Retrying re-schedules on
    a surviving node. Replaces the string-matched
    ``ValueError("scheduled node vanished; retry")`` signal."""

    def __init__(self, node_id_hex: str = "", detail: str = ""):
        self.node_id_hex = node_id_hex
        msg = f"scheduled node {node_id_hex or '?'} vanished"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class PlacementInfeasibleError(RetryableError):
    """A placement request does not fit the head's *current* availability
    view — which lags heartbeats and is optimistically debited, so
    transient infeasibility is normal and retried under a bounded
    deadline (PG creation). Replaces the string-matched ``"infeasible"``
    ValueError signal."""


class DeadlineExceeded(FatalError, TimeoutError):
    """The caller's remaining time budget is spent. Raised *locally*,
    before touching the socket, when a propagated deadline expires —
    never worth retrying under the same deadline."""

    def __init__(self, what: str = "operation",
                 budget_s: Optional[float] = None,
                 overrun_s: Optional[float] = None):
        self.what = what
        self.budget_s = budget_s
        self.overrun_s = overrun_s
        msg = f"deadline exceeded for {what}"
        if budget_s is not None:
            msg += f" (budget {budget_s:.3f}s"
            if overrun_s is not None:
                msg += f", overran by {overrun_s:.3f}s"
            msg += ")"
        super().__init__(msg)


class CircuitOpenError(FatalError):
    """The per-peer circuit breaker is open: the peer has failed
    consecutively past threshold and the cooldown has not elapsed.
    Fail-fast — callers degrade (partial results, alternate replica)
    instead of queueing behind a dead socket."""

    def __init__(self, peer: str, open_for_s: Optional[float] = None):
        self.peer = peer
        self.open_for_s = open_for_s
        msg = f"circuit breaker open for peer {peer}"
        if open_for_s is not None:
            msg += f" (retry allowed in {open_for_s:.3f}s)"
        super().__init__(msg)


class RpcTimeoutError(RetryableError, TimeoutError):
    """An RPC reply did not arrive within the configured timeout.
    Carries full call context (method, peer, timeout, elapsed) so a
    stack trace names the slow hop instead of 'rpc call timed out'."""

    def __init__(self, method: str = "?", peer: str = "?",
                 timeout_s: Optional[float] = None,
                 elapsed_s: Optional[float] = None):
        self.method = method
        self.peer = peer
        self.timeout_s = timeout_s
        self.elapsed_s = elapsed_s
        msg = f"rpc {method!r} to {peer} timed out"
        if timeout_s is not None:
            msg += f" after {timeout_s:.3f}s"
        if elapsed_s is not None:
            msg += f" (elapsed {elapsed_s:.3f}s)"
        super().__init__(msg)


class TenantThrottled(RetryableError):
    """Admission control shed this submission: the tenant's queued-spec
    budget on the head is exhausted (``RAYTPU_TENANT_MAX_QUEUED``).
    Carries ``retry_after_s`` so the client's
    :class:`~raytpu.util.resilience.RetryPolicy` backs off at least that
    long before re-submitting instead of hammering an overloaded head.

    ``args`` is kept positional-and-primitive — the wire rebuilds
    exceptions via ``cls(*args)``, and ``retry_after_s`` must survive
    the hop because the client acts on it."""

    def __init__(self, tenant: str = "", retry_after_s: float = 0.0,
                 detail: str = ""):
        self.tenant = tenant
        self.retry_after_s = float(retry_after_s or 0.0)
        self.detail = detail
        super().__init__(tenant, self.retry_after_s, detail)

    def __str__(self) -> str:
        msg = f"tenant {self.tenant or '?'} throttled"
        if self.retry_after_s:
            msg += f" (retry after {self.retry_after_s:.3f}s)"
        if self.detail:
            msg += f": {self.detail}"
        return msg


_SWALLOWED: "Dict[str, int]" = {}


def swallow(where: str, exc: BaseException) -> None:
    """Record an intentionally-tolerated failure at a cluster seam.

    Best-effort paths (notify fan-out, teardown, metrics push) are
    *allowed* to tolerate peer failures — but a silent ``pass`` erases
    the only evidence of a sick peer. This helper is the sanctioned
    swallow: it bumps a per-seam counter and debug-logs the exception,
    and is guaranteed never to raise, so it is safe in ``finally`` and
    teardown paths. ``swallowed_counts()`` exposes the tallies for
    post-mortems and tests.
    """
    try:
        _SWALLOWED[where] = _SWALLOWED.get(where, 0) + 1
        logging.getLogger("raytpu.errors").debug(
            "swallowed at %s: %r", where, exc)
    except Exception:  # the never-raise contract trumps reporting
        pass


def swallowed_counts() -> "Dict[str, int]":
    """Per-seam tallies of swallowed failures (copy)."""
    return dict(_SWALLOWED)


def is_retryable(exc: BaseException) -> bool:
    """Classify an exception for retry policies.

    Taxonomy types answer for themselves; pre-taxonomy types are
    classified by kind: connection-level failures and plain timeouts are
    transient (the peer may come back / the next attempt may be faster),
    while everything else — application errors — means the operation
    itself is wrong and retrying would just repeat it.

    Order matters: :class:`DeadlineExceeded` subclasses ``TimeoutError``
    but is fatal (same budget, same outcome), so ``FatalError`` is
    checked first.
    """
    if isinstance(exc, FatalError):
        return False
    if isinstance(exc, RetryableError):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if isinstance(exc, OSError):
        return True
    # ConnectionLost (protocol.py) subclasses RpcError/Exception only;
    # match it structurally to avoid an import cycle with protocol.py.
    if type(exc).__name__ == "ConnectionLost":
        return True
    return False
