"""Distributed tracing & profiling.

Reference analogue (SURVEY.md §5 tracing): (a) span wrapping of task/actor
calls (``python/ray/util/tracing/tracing_helper.py:34``, OpenTelemetry);
(b) chrome-trace timeline from buffered profile events (``ray timeline``,
``python/ray/_private/state.py:917``); (c) on-demand worker profiling.

Cross-process model (Dapper): a :class:`TraceContext` — trace id, span id,
parent span id, sampled flag — rides every RPC frame as a ``"tc"`` field
next to the deadline's ``"d"`` (see :mod:`raytpu.cluster.protocol`) and is
re-anchored server-side into a contextvar, so a driver's submit span is
the ancestor of the head's scheduling span and the worker's execution
span. Each process records closed spans into a bounded ring buffer;
``trace_dump`` RPCs fan the buffers back (head → nodes → workers) and
:func:`assemble_timeline` merges them into one chrome-trace/Perfetto JSON
with per-process tracks and flow arrows on cross-process parent edges.

Cost model mirrors :mod:`raytpu.util.failpoints`: with tracing disabled a
span site is one module-flag check plus returning a shared no-op context
manager — nothing allocates, no contextvar is read (pinned by the
micro-bench in tests/test_tracing.py). Arming is inherited by child
processes via ``RAYTPU_TRACING`` / ``RAYTPU_TRACE_SAMPLE`` env vars.

TPU-first: device-side profiling is ``jax.profiler`` (XLA traces viewable
in TensorBoard/Perfetto include per-op HBM/MXU utilization), host-side is
the task-event timeline the backend already buffers. Both are exposed
here: ``profile()`` wraps a region with a jax profiler trace; ``timeline``
dumps chrome-trace JSON of task events.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

ENV_VAR = "RAYTPU_TRACING"
SAMPLE_ENV_VAR = "RAYTPU_TRACE_SAMPLE"
BUFFER_ENV_VAR = "RAYTPU_TRACE_BUFFER"


def _env_truthy(name: str) -> bool:
    return os.environ.get(name, "") not in ("", "0", "false", "False")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


_BUFFER = max(16, int(_env_float(BUFFER_ENV_VAR, 4096)))
_spans: "deque[dict]" = deque(maxlen=_BUFFER)
_spans_lock = threading.Lock()
_enabled = _env_truthy(ENV_VAR)
_sample_rate = _env_float(SAMPLE_ENV_VAR, 1.0)
# [kind, ident] — e.g. ["head", ""], ["worker", "ab12cd34"]. Mutated in
# place so dump() sees updates without rebinding.
_identity: List[str] = ["proc", ""]


class TraceContext:
    """Immutable Dapper-style context: which trace, which span, whose
    child, and whether anything records. On the wire only
    ``[trace_id, span_id, sampled]`` travels — the receiver's parent IS
    the sender's span id, so ``parent_span_id`` never needs to ride."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: Optional[str] = None,
                 sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.sampled = sampled

    @classmethod
    def root(cls, sampled: bool = True) -> "TraceContext":
        return cls(os.urandom(16).hex(), os.urandom(8).hex(), None, sampled)

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, os.urandom(8).hex(),
                            self.span_id, self.sampled)

    def to_wire(self) -> list:
        # Primitives only: must encode on strict (allow_pickle=False)
        # surfaces like the driver proxy.
        return [self.trace_id, self.span_id, 1 if self.sampled else 0]

    @classmethod
    def from_wire(cls, w: Any) -> Optional["TraceContext"]:
        try:
            trace_id, span_id, sampled = w[0], w[1], bool(w[2])
        except (TypeError, IndexError, KeyError):
            return None
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        return cls(trace_id, span_id, None, sampled)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext({self.trace_id[:8]}…/{self.span_id}"
                f" parent={self.parent_span_id} sampled={self.sampled})")


_current: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("raytpu_trace", default=None)


def current_trace() -> Optional[TraceContext]:
    """The ambient trace context (None outside any span/handler)."""
    return _current.get()


def set_current_trace(ctx: Optional[TraceContext]):
    """Anchor ``ctx`` as the ambient context; returns a reset token."""
    return _current.set(ctx)


def reset_current_trace(token) -> None:
    _current.reset(token)


def enabled() -> bool:
    return _enabled


def enable_tracing(sample_rate: Optional[float] = None,
                   env: bool = False) -> None:
    """Turn on span capture (reference: tracing startup hook enables the
    OpenTelemetry proxy). ``sample_rate`` bounds ROOT creation: 0.0 means
    new roots are created unsampled (contexts still propagate, nothing
    records). ``env=True`` exports the arming so child processes — cluster
    daemons, pool workers — inherit it (failpoints' ``cfg(env=True)``
    pattern)."""
    global _enabled, _sample_rate
    if sample_rate is not None:
        _sample_rate = float(sample_rate)
    _enabled = True
    if env:
        os.environ[ENV_VAR] = "1"
        os.environ[SAMPLE_ENV_VAR] = repr(_sample_rate)


def disable_tracing(env: bool = False) -> None:
    global _enabled
    _enabled = False
    if env:
        os.environ.pop(ENV_VAR, None)
        os.environ.pop(SAMPLE_ENV_VAR, None)


def set_process_identity(kind: str, ident: str = "") -> None:
    """Name this process for cluster timelines (head / node:<id> /
    worker:<id> / driver)."""
    _identity[0] = str(kind)
    _identity[1] = str(ident)


def get_spans() -> List[dict]:
    with _spans_lock:
        return list(_spans)


def clear_spans() -> None:
    with _spans_lock:
        _spans.clear()


def dump() -> dict:
    """This process's span buffer plus identity — the payload of the
    ``trace_dump`` RPC every daemon registers."""
    return {"identity": list(_identity), "pid": os.getpid(),
            "spans": get_spans()}


_NOOP_ATTRS: Dict[str, Any] = {}


class _NoopSpan:
    """Shared disabled-path context manager: zero allocation per site."""

    __slots__ = ()

    def __enter__(self) -> Dict[str, Any]:
        # Sites may write attributes into the yielded dict; a shared one
        # is fine because nothing ever reads it. Bounded by the set of
        # distinct attribute keys, not by call count.
        return _NOOP_ATTRS

    def __exit__(self, et, ev, tb) -> bool:
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    """Recording context manager. Entering derives a child context from
    the ambient one (or starts a new root, subject to the sample rate)
    and anchors it; exiting restores the parent and — only when sampled —
    appends one record to the ring buffer."""

    __slots__ = ("name", "attrs", "_ctx", "_token", "_start", "_t0")

    def __init__(self, name: str, attributes: Optional[Dict] = None):
        self.name = name
        self.attrs: Dict[str, Any] = dict(attributes) if attributes else {}

    def __enter__(self) -> Dict[str, Any]:
        parent = _current.get()
        if parent is not None:
            self._ctx = parent.child()
        else:
            sampled = _sample_rate >= 1.0 or random.random() < _sample_rate
            self._ctx = TraceContext.root(sampled=sampled)
        self._token = _current.set(self._ctx)
        self._start = time.time()
        self._t0 = time.perf_counter()
        return self.attrs

    def __exit__(self, et, ev, tb) -> bool:
        dur = time.perf_counter() - self._t0
        _current.reset(self._token)
        ctx = self._ctx
        if ctx.sampled:
            with _spans_lock:
                _spans.append({
                    "name": self.name,
                    "trace_id": ctx.trace_id,
                    "span_id": ctx.span_id,
                    "parent_span_id": ctx.parent_span_id,
                    "start": self._start,
                    "duration_s": dur,
                    "pid": os.getpid(),
                    "tid": threading.get_native_id(),
                    "attributes": self.attrs,
                    "error": repr(ev) if ev is not None else None,
                })
        return False


def span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """One traced region. Disabled cost is this flag check plus a shared
    no-op context manager; enabled, it parents into the ambient
    :class:`TraceContext` and records into the ring buffer. Yields the
    (mutable) attributes dict so sites can attach results post-hoc::

        with tracing.span("sched.decide") as attrs:
            node = pick()
            attrs["node"] = node
    """
    if not _enabled:
        return _NOOP_SPAN
    return _Span(name, attributes)


def traced(name: Optional[str] = None) -> Callable:
    """Decorator version of :func:`span`."""

    def wrap(fn: Callable) -> Callable:
        label = name or getattr(fn, "__qualname__", "fn")

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(label):
                return fn(*args, **kwargs)

        return inner

    return wrap


def run_with_trace(tc: Optional[TraceContext], name: str,
                   fn: Callable, *args, **kwargs):
    """Re-anchor ``tc`` around ``fn`` on THIS thread and run it inside a
    span. The bridge for every hop that loses contextvars: executor
    offloads (``run_in_executor`` does not copy context) and
    queue-decoupled execution (a task enqueued by one RPC and executed
    later by a dispatcher thread)."""
    token = _current.set(tc) if tc is not None else None
    try:
        with span(name):
            return fn(*args, **kwargs)
    finally:
        if token is not None:
            _current.reset(token)


def _span_event(s: dict, pid: Optional[int] = None) -> dict:
    args = dict(s.get("attributes") or {})
    for k in ("trace_id", "span_id", "parent_span_id"):
        if s.get(k):
            args[k] = s[k]
    if s.get("error"):
        args["error"] = s["error"]
    return {
        "name": s["name"],
        "cat": "span",
        "ph": "X",
        "ts": s["start"] * 1e6,
        "dur": s["duration_s"] * 1e6,
        "pid": s.get("pid", 0) if pid is None else pid,
        "tid": s.get("tid", 0),
        "args": args,
    }


@contextlib.contextmanager
def profile(logdir: str, *, host_tracer_level: int = 2):
    """XLA device profiling for the enclosed region. Produces a trace
    viewable in TensorBoard's profiler / Perfetto (per-op timing, HBM
    pressure, MXU utilization — the TPU analogue of the reference's
    nsight runtime-env plugin)."""
    import jax

    jax.profiler.start_trace(logdir, create_perfetto_trace=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-trace events from the backend's task-event buffer plus any
    locally recorded spans (reference: ``ray timeline``). Spans carry
    their real pid/tid so a multi-threaded local timeline lays out on
    distinct tracks. For the whole cluster, see
    :func:`cluster_timeline`."""
    import raytpu

    events = raytpu.timeline()
    trace = list(events) if isinstance(events, list) else []
    for s in get_spans():
        trace.append(_span_event(s))
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


def assemble_timeline(dumps: List[dict],
                      filename: Optional[str] = None) -> List[dict]:
    """Merge per-process trace dumps (:func:`dump` payloads) into one
    chrome-trace JSON. Each dump becomes one ``pid`` track named by its
    identity via a ``process_name`` metadata event; spans whose parent
    lives in a DIFFERENT process get a flow-event pair (``ph:"s"`` at the
    parent, ``ph:"f", bp:"e"`` at the child) so Perfetto draws the
    cross-process arrow."""
    events: List[dict] = []
    # span_id -> (track pid, record)
    index: Dict[str, Tuple[int, dict]] = {}
    for i, d in enumerate(dumps or []):
        if not isinstance(d, dict):
            continue
        ident = list(d.get("identity") or ("proc", ""))
        label = str(ident[0]) if ident else "proc"
        if len(ident) > 1 and ident[1]:
            label += f":{ident[1]}"
        label += f" (pid {d.get('pid', '?')})"
        track = i + 1
        events.append({"name": "process_name", "ph": "M", "pid": track,
                       "tid": 0, "args": {"name": label}})
        for s in d.get("spans") or []:
            events.append(_span_event(s, pid=track))
            sid = s.get("span_id")
            if sid:
                index[sid] = (track, s)
    for sid, (track, s) in index.items():
        parent = s.get("parent_span_id")
        if not parent or parent not in index:
            continue
        ptrack, ps = index[parent]
        if ptrack == track:
            continue  # local nesting draws itself; arrows are for hops
        events.append({
            "name": "trace", "cat": "flow", "ph": "s", "id": sid,
            "pid": ptrack, "tid": ps.get("tid", 0),
            "ts": ps["start"] * 1e6,
        })
        events.append({
            "name": "trace", "cat": "flow", "ph": "f", "bp": "e",
            "id": sid, "pid": track, "tid": s.get("tid", 0),
            "ts": s["start"] * 1e6,
        })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def cluster_timeline(filename: Optional[str] = None) -> List[dict]:
    """Pull every process's span buffer through the connected backend's
    ``trace_dump`` fan-out (driver → head → nodes → workers) and
    assemble one cluster-wide chrome trace. Falls back to just the local
    process when not connected to a cluster."""
    dumps: List[dict] = []
    try:
        from raytpu.runtime import api as _api

        backend = _api._backend_or_none()
    except Exception:  # pragma: no cover - api import never fails in-tree
        backend = None
    if backend is not None and hasattr(backend, "trace_dump"):
        try:
            dumps = list(backend.trace_dump() or [])
        except Exception:
            dumps = []
    # The head's fan-out can reach this very process (a connected driver
    # runs a serve-only node daemon): drop that copy in favor of the
    # local buffer, which is strictly fresher, or the driver would get
    # two identical tracks.
    me = os.getpid()
    dumps = [d for d in dumps
             if not (isinstance(d, dict) and d.get("pid") == me)]
    dumps.append(dump())  # this (driver) process
    return assemble_timeline(dumps, filename)
