"""Tracing & profiling.

Reference analogue (SURVEY.md §5 tracing): (a) span wrapping of task/actor
calls (``python/ray/util/tracing/tracing_helper.py:34``, OpenTelemetry);
(b) chrome-trace timeline from buffered profile events (``ray timeline``,
``python/ray/_private/state.py:917``); (c) on-demand worker profiling.

TPU-first: device-side profiling is ``jax.profiler`` (XLA traces viewable
in TensorBoard/Perfetto include per-op HBM/MXU utilization), host-side is
the task-event timeline the backend already buffers. Both are exposed
here: ``profile()`` wraps a region with a jax profiler trace; ``timeline``
dumps chrome-trace JSON of task events.
"""

from __future__ import annotations

import contextlib
import functools
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_spans: List[dict] = []
_spans_lock = threading.Lock()
_enabled = False


def enable_tracing() -> None:
    """Turn on span capture for traced functions (reference: tracing
    startup hook enables the OpenTelemetry proxy)."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def get_spans() -> List[dict]:
    with _spans_lock:
        return list(_spans)


def clear_spans() -> None:
    with _spans_lock:
        _spans.clear()


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Record one span (no-op unless tracing is enabled)."""
    if not _enabled:
        yield
        return
    start = time.time()
    err = None
    try:
        yield
    except BaseException as e:
        err = repr(e)
        raise
    finally:
        with _spans_lock:
            _spans.append({
                "name": name,
                "start": start,
                "duration_s": time.time() - start,
                "attributes": dict(attributes or {}),
                "error": err,
            })


def traced(name: Optional[str] = None) -> Callable:
    """Decorator version of :func:`span`."""

    def wrap(fn: Callable) -> Callable:
        label = name or getattr(fn, "__qualname__", "fn")

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with span(label):
                return fn(*args, **kwargs)

        return inner

    return wrap


@contextlib.contextmanager
def profile(logdir: str, *, host_tracer_level: int = 2):
    """XLA device profiling for the enclosed region. Produces a trace
    viewable in TensorBoard's profiler / Perfetto (per-op timing, HBM
    pressure, MXU utilization — the TPU analogue of the reference's
    nsight runtime-env plugin)."""
    import jax

    jax.profiler.start_trace(logdir, create_perfetto_trace=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-trace events from the backend's task-event buffer plus any
    recorded spans (reference: ``ray timeline``)."""
    import raytpu

    events = raytpu.timeline()
    trace = list(events) if isinstance(events, list) else []
    for s in get_spans():
        trace.append({
            "name": s["name"],
            "cat": "span",
            "ph": "X",
            "ts": s["start"] * 1e6,
            "dur": s["duration_s"] * 1e6,
            "pid": 0,
            "tid": 0,
            "args": s["attributes"],
        })
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
