"""Head-side cluster profile store fed by shipped collapsed-stack
frames (util/profiler.py continuous shipping).

The TSDB sibling: per-proc rings of thread-folded snapshots under one
hard byte cap with FIFO eviction, per-origin seq dedup so a requeued
re-ship merges once, and node-death tombstones matched by the same
hex12-prefix convention ``MetricStore`` uses — a node that died
mid-ship can neither resurrect stale stacks nor leak ring slots.

Queries: ``merged`` (one cluster flamegraph over a time window) and
``diff`` (recent window minus the preceding window, signed per stack),
serving ``raytpu profile --continuous/--diff``, the dashboard's
``GET /api/profile?source=store``, and post-mortem dumps.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from raytpu.util.profiler import diff_collapsed, merge_collapsed


class ProfileStore:
    """Bounded in-memory store behind the head's ``profile_*`` RPCs."""

    def __init__(self, max_bytes: int = 4_000_000,
                 ring_slots: int = 120,
                 clock: Callable[[], float] = time.time):
        self.max_bytes = int(max_bytes)
        self.ring_slots = int(ring_slots)
        self._clock = clock
        self._lock = threading.Lock()
        # proc -> deque[(ts, collapsed, samples, window_s, cost)]
        self._rings: Dict[str, Deque[tuple]] = {}
        self._proc_seq: Dict[str, int] = {}
        self._proc_dropped: Dict[str, int] = {}  # upstream sample-ship drops
        self._proc_last: Dict[str, float] = {}
        self._dead_procs: set = set()            # hex12 node prefixes
        self._bytes = 0
        self.frames_applied = 0
        self.frames_deduped = 0
        self.frames_rejected = 0                 # tombstoned origin
        self.frames_dropped = 0                  # malformed
        self.frames_evicted = 0
        self.upstream_drops = 0

    # -- ingest ------------------------------------------------------------

    @staticmethod
    def _cost(collapsed: Dict[str, int]) -> int:
        return 64 + sum(len(k) + 16 for k in collapsed)

    def push(self, frames: List[list]) -> int:
        """Apply shipped snapshot frames; returns how many applied.
        Idempotent per origin: ``seq`` <= last-applied is a duplicate."""
        applied = 0
        with self._lock:
            for frame in frames or ():
                try:
                    proc, seq, ts, collapsed, samples, window_s = frame
                    proc = str(proc)
                    seq = int(seq)
                    ts = float(ts)
                    samples = int(samples)
                    window_s = float(window_s)
                    if not isinstance(collapsed, dict):
                        raise TypeError("collapsed must be a dict")
                    collapsed = {str(k): int(v)
                                 for k, v in collapsed.items()}
                except (TypeError, ValueError):
                    self.frames_dropped += 1
                    continue
                if self._proc_dead(proc):
                    self.frames_rejected += 1
                    continue
                if seq <= self._proc_seq.get(proc, 0):
                    self.frames_deduped += 1
                    continue
                self._proc_seq[proc] = seq
                ring = self._rings.get(proc)
                if ring is None:
                    ring = self._rings[proc] = deque()
                cost = self._cost(collapsed)
                if len(ring) >= self.ring_slots:
                    old = ring.popleft()
                    self._bytes -= old[4]
                    self.frames_evicted += 1
                ring.append((ts, collapsed, samples, window_s, cost))
                self._bytes += cost
                self._proc_last[proc] = ts
                self._make_room()
                applied += 1
                self.frames_applied += 1
        return applied

    def _make_room(self) -> None:
        """FIFO-evict the globally-oldest snapshot until under the cap
        (proc count is small; a linear scan per eviction is fine)."""
        while self._bytes > self.max_bytes:
            victim = None
            oldest = float("inf")
            for proc, ring in self._rings.items():
                if ring and ring[0][0] < oldest:
                    oldest = ring[0][0]
                    victim = proc
            if victim is None:
                return
            old = self._rings[victim].popleft()
            self._bytes -= old[4]
            self.frames_evicted += 1
            if not self._rings[victim]:
                del self._rings[victim]

    def note_upstream_drops(self, n: int, proc: str = "") -> None:
        """Frames lost before reaching us (buffer overflow at the origin
        or a lost ship leg), attributed to the shipping carrier so
        ``raytpu top --profile`` can name the lossy proc."""
        n = int(n or 0)
        if n <= 0:
            return
        with self._lock:
            self.upstream_drops += n
            if proc:
                proc = str(proc)
                self._proc_dropped[proc] = \
                    self._proc_dropped.get(proc, 0) + n

    # -- liveness ----------------------------------------------------------

    def _proc_dead(self, proc: str) -> bool:
        for p in self._dead_procs:
            if proc in (f"node:{p}", f"driver:{p}") or \
                    proc.startswith(f"worker:{p}."):
                return True
        return False

    def mark_proc_dead(self, node_hex12: str) -> int:
        """Tombstone every proc rooted at this node: drop their rings
        now and reject any late frame (same contract as the TSDB)."""
        p = str(node_hex12)[:12]
        removed = 0
        with self._lock:
            self._dead_procs.add(p)
            doomed = [q for q in self._rings if self._proc_dead(q)]
            for q in doomed:
                ring = self._rings.pop(q)
                self._bytes -= sum(e[4] for e in ring)
                removed += len(ring)
            for q in [q for q in self._proc_seq if self._proc_dead(q)]:
                del self._proc_seq[q]
                self._proc_last.pop(q, None)
        return removed

    def revive_proc(self, node_hex12: str) -> None:
        """A (re-)registered node sheds its tombstone so shipping
        resumes — the head-bounce / node-reconnect path."""
        with self._lock:
            self._dead_procs.discard(str(node_hex12)[:12])

    # -- query -------------------------------------------------------------

    def merged(self, since_s: float = 600.0, until_s: float = 0.0,
               procs: Optional[List[str]] = None,
               now: Optional[float] = None) -> Dict:
        """One cluster-wide flamegraph: every snapshot whose ts falls in
        ``[now - since_s, now - until_s]``, merged deterministically."""
        if now is None:
            now = self._clock()
        lo, hi = now - float(since_s), now - float(until_s)
        parts: List[Dict[str, int]] = []
        samples = 0
        used: List[str] = []
        frames = 0
        with self._lock:
            for proc in sorted(self._rings):
                if procs and proc not in procs:
                    continue
                hit = False
                for ts, collapsed, n, _w, _c in self._rings[proc]:
                    if lo <= ts <= hi:
                        parts.append(collapsed)
                        samples += n
                        frames += 1
                        hit = True
                if hit:
                    used.append(proc)
        return {"collapsed": merge_collapsed(parts), "samples": samples,
                "frames": frames, "procs": used,
                "since_s": float(since_s), "until_s": float(until_s)}

    def diff(self, recent_s: float = 120.0,
             now: Optional[float] = None) -> Dict:
        """Signed delta: the last ``recent_s`` seconds minus the
        ``recent_s`` seconds before that — what got hotter since."""
        if now is None:
            now = self._clock()
        recent = self.merged(recent_s, 0.0, now=now)
        baseline = self.merged(2 * recent_s, recent_s, now=now)
        return {"delta": diff_collapsed(recent["collapsed"],
                                        baseline["collapsed"]),
                "recent": recent, "baseline": baseline,
                "recent_s": float(recent_s)}

    def proc_rows(self) -> List[Dict]:
        """Per-proc inventory for ``raytpu top --profile``."""
        with self._lock:
            procs = sorted(set(self._rings) | set(self._proc_dropped))
            return [{"proc": p,
                     "frames": len(self._rings.get(p, ())),
                     "samples": sum(e[2] for e in self._rings.get(p, ())),
                     "last_ts": self._proc_last.get(p, 0.0),
                     "dropped": self._proc_dropped.get(p, 0)}
                    for p in procs]

    def stats(self) -> Dict:
        with self._lock:
            return {"procs": len(self._rings),
                    "frames": sum(len(r) for r in self._rings.values()),
                    "bytes": self._bytes,
                    "max_bytes": self.max_bytes,
                    "frames_applied": self.frames_applied,
                    "frames_deduped": self.frames_deduped,
                    "frames_rejected": self.frames_rejected,
                    "frames_dropped": self.frames_dropped,
                    "frames_evicted": self.frames_evicted,
                    "upstream_drops": self.upstream_drops,
                    "dead_procs": sorted(self._dead_procs)}
