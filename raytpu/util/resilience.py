"""Unified resilience layer: retry policies, circuit breakers, deadlines.

Reference analogues: gRPC's retry policy + deadline propagation model
(deadlines shrink monotonically as a call crosses hops; a server sees
the *caller's* remaining budget, not a fresh one) and the circuit
breaker of Nygard's *Release It!* as implemented in Hystrix/resilience4j
(closed → open on consecutive failures, half-open probe after cooldown).
Ray's equivalent machinery is scattered through ``core_worker`` retry
loops; here it is one policy surface the whole cluster layer shares.

Three primitives:

- :class:`RetryPolicy` — bounded attempts, exponential backoff with
  *deterministically seeded* jitter (chaos tests pin the exact delay
  sequence), retryability decided by the typed taxonomy in
  :mod:`raytpu.util.errors` (never by string-matching messages).
- :class:`CircuitBreaker` — per-peer failure accounting. One dead peer
  must cost each caller O(1) probes, not O(attempts); the breaker turns
  repeated connect-and-burn into an instant local
  :class:`~raytpu.util.errors.CircuitOpenError`.
- :class:`Deadline` — an absolute time budget that rides RPC frame
  metadata (wire format: *remaining seconds* as a float, because peer
  clocks are not synchronized) and shrinks across hops. Expiry raises
  :class:`~raytpu.util.errors.DeadlineExceeded` locally, before the
  socket is touched.

Clocks and sleeps are injectable so every behavior is testable without
wall-clock waits.
"""

from __future__ import annotations

import contextvars
import os
import random
import threading
import time
from typing import Any, Callable, Dict, Optional

from raytpu.util.errors import (
    CircuitOpenError,
    DeadlineExceeded,
    is_retryable,
)

# Env-overridable defaults (RAYTPU_* convention, matching the heartbeat
# constants in cluster/head.py and the timeout registry in
# cluster/constants.py — kept here because util/ must not import cluster/).
RETRY_MAX_ATTEMPTS = int(os.environ.get("RAYTPU_RETRY_MAX_ATTEMPTS", "3"))
RETRY_BASE_DELAY_S = float(os.environ.get("RAYTPU_RETRY_BASE_DELAY_S", "0.05"))
RETRY_MAX_DELAY_S = float(os.environ.get("RAYTPU_RETRY_MAX_DELAY_S", "2.0"))
BREAKER_FAILURE_THRESHOLD = int(
    os.environ.get("RAYTPU_BREAKER_FAILURE_THRESHOLD", "5"))
BREAKER_RESET_TIMEOUT_S = float(
    os.environ.get("RAYTPU_BREAKER_RESET_TIMEOUT_S", "5.0"))


# -- deadlines ---------------------------------------------------------------


class Deadline:
    """Absolute expiry against a monotonic clock.

    Created once at the outermost caller (``Deadline.after(total)``) and
    passed *down* — every layer that consumes time shrinks what the next
    layer sees. Serialization is relative (:meth:`to_wire` → remaining
    seconds) so the budget survives hops between machines whose clocks
    disagree.
    """

    __slots__ = ("_expires_at", "_clock")

    def __init__(self, expires_at: float,
                 clock: Callable[[], float] = time.monotonic):
        self._expires_at = expires_at
        self._clock = clock

    @classmethod
    def after(cls, seconds: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(clock() + float(seconds), clock=clock)

    def remaining(self) -> float:
        """Seconds left; negative once expired (callers that report
        overrun want the sign)."""
        return self._expires_at - self._clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "operation") -> None:
        """Raise :class:`DeadlineExceeded` if the budget is spent."""
        rem = self.remaining()
        if rem <= 0.0:
            raise DeadlineExceeded(what, overrun_s=-rem)

    def bound(self, timeout: Optional[float]) -> float:
        """Shrink a per-call timeout to fit the remaining budget.

        ``timeout=None`` (wait forever) becomes the remaining budget —
        a deadlined call is never unbounded. Floor of 0: a spent budget
        yields an immediate timeout rather than a negative wait.
        """
        rem = max(0.0, self.remaining())
        if timeout is None:
            return rem
        return min(float(timeout), rem)

    def to_wire(self) -> float:
        """Frame metadata: remaining seconds (relative — peer clocks are
        not synchronized, so absolute times cannot cross the wire)."""
        return self.remaining()

    @classmethod
    def from_wire(cls, remaining_s: float) -> "Deadline":
        return cls.after(float(remaining_s))

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


# Server-side propagation: RpcServer._dispatch decodes the frame's "d"
# field into a Deadline and sets it here for the duration of the handler.
# Each dispatch runs in its own asyncio task (contextvars are copied at
# task creation), so concurrent requests on one connection can't race.
_current_deadline: "contextvars.ContextVar[Optional[Deadline]]" = \
    contextvars.ContextVar("raytpu_deadline", default=None)


def current_deadline() -> Optional[Deadline]:
    """The deadline of the RPC being handled, if the caller sent one.
    Handlers that fan out downstream pass this along so the budget keeps
    shrinking hop by hop (client → head → relay → node)."""
    return _current_deadline.get()


def set_current_deadline(d: Optional[Deadline]) -> "contextvars.Token":
    return _current_deadline.set(d)


def reset_current_deadline(token: "contextvars.Token") -> None:
    _current_deadline.reset(token)


# -- retry policy ------------------------------------------------------------


class RetryPolicy:
    """Bounded retry with exponential backoff and seeded jitter.

    ``delay(k) = min(max_delay_s, base_delay_s * multiplier**k)
    * (1 + jitter * u_k)`` where ``u_k`` is the k-th draw from
    ``random.Random(seed)`` — fix the seed and the whole delay sequence
    is pinned, which is what lets chaos tests assert exact backoff
    without tolerance windows.

    ``retryable`` defaults to the taxonomy classifier
    (:func:`raytpu.util.errors.is_retryable`); ``sleep`` is injectable
    so tests record delays instead of serving them.
    """

    def __init__(self, max_attempts: int = RETRY_MAX_ATTEMPTS,
                 base_delay_s: float = RETRY_BASE_DELAY_S,
                 max_delay_s: float = RETRY_MAX_DELAY_S,
                 multiplier: float = 2.0,
                 jitter: float = 0.5,
                 seed: Optional[int] = None,
                 retryable: Callable[[BaseException], bool] = is_retryable,
                 sleep: Callable[[float], None] = time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_delay_s = base_delay_s
        self.max_delay_s = max_delay_s
        self.multiplier = multiplier
        self.jitter = jitter
        self.seed = seed
        self.retryable = retryable
        self._sleep = sleep

    def delays(self) -> list:
        """The full backoff schedule (``max_attempts - 1`` entries),
        deterministic for a fixed seed."""
        rng = random.Random(self.seed)
        out = []
        for k in range(self.max_attempts - 1):
            base = min(self.max_delay_s,
                       self.base_delay_s * (self.multiplier ** k))
            out.append(base * (1.0 + self.jitter * rng.random()))
        return out

    def run(self, fn: Callable[[], Any], *,
            deadline: Optional[Deadline] = None,
            what: str = "operation",
            on_retry: Optional[Callable[[int, BaseException, float],
                                        None]] = None) -> Any:
        """Call ``fn`` up to ``max_attempts`` times.

        Non-retryable errors and the final attempt's error propagate
        unchanged. A deadline bounds the whole loop: expiry is checked
        before each attempt, and a backoff that would sleep past the
        deadline re-raises instead of burning budget in bed.
        """
        rng = random.Random(self.seed)
        attempt = 0
        while True:
            if deadline is not None:
                deadline.check(what)
            attempt += 1
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 — classifier decides
                if attempt >= self.max_attempts or not self.retryable(e):
                    raise
                base = min(self.max_delay_s,
                           self.base_delay_s
                           * (self.multiplier ** (attempt - 1)))
                delay = base * (1.0 + self.jitter * rng.random())
                # A server-directed backoff floors the computed delay:
                # errors like TenantThrottled carry retry_after_s — the
                # head said when it is worth coming back, and retrying
                # sooner just deepens the overload being shed.
                hint = float(getattr(e, "retry_after_s", 0.0) or 0.0)
                if hint > delay:
                    delay = hint
                if deadline is not None and deadline.remaining() <= delay:
                    raise  # sleeping would outlive the budget
                if on_retry is not None:
                    try:
                        on_retry(attempt, e, delay)
                    except Exception:
                        pass
                try:
                    from raytpu.util import task_events

                    if task_events.enabled():
                        task_events.emit(
                            "node", what,
                            task_events.TaskTransition.RETRIED,
                            attempt=attempt, error=type(e).__name__)
                except Exception:
                    pass
                try:
                    # Exception class name keeps tag cardinality bounded
                    # (vs. str(e), which embeds addresses/ids).
                    m = _metric("counter", "raytpu_retries_total",
                                "retry attempts across resilience "
                                "policies", ("error",))
                    if m is not None:
                        m.inc(1.0, tags={"error": type(e).__name__})
                except Exception:
                    pass
                self._sleep(delay)


# -- circuit breaker ---------------------------------------------------------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_metrics_lock = threading.Lock()
_metrics: Dict[str, Any] = {}


def _metric(kind: str, name: str, desc: str, tag_keys):
    """Lazy, best-effort metric creation (the breaker must work — and
    stay silent — even if the metrics registry objects to anything)."""
    with _metrics_lock:
        m = _metrics.get(name)
        if m is None:
            try:
                from raytpu.util import metrics as _m

                cls = {"counter": _m.Counter, "gauge": _m.Gauge,
                       "histogram": _m.Histogram}[kind]
                m = cls(name, desc, tag_keys=tag_keys)
            except Exception:
                m = False  # cache the failure; never retry per-call
            _metrics[name] = m
    return m or None


class CircuitBreaker:
    """Per-peer consecutive-failure breaker (closed → open → half-open).

    Only *transport-level* outcomes feed the state machine: the owner
    records a failure when the peer was unreachable or silent, and a
    success when a reply arrived — even an application error is proof
    the peer is alive. ``clock`` is injectable so the open→half-open
    cooldown is testable without waiting it out.
    """

    def __init__(self, peer: str = "",
                 failure_threshold: int = BREAKER_FAILURE_THRESHOLD,
                 reset_timeout_s: float = BREAKER_RESET_TIMEOUT_S,
                 half_open_max_probes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.peer = peer
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max_probes = half_open_max_probes
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes = 0

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _maybe_half_open_locked(self) -> None:
        if (self._state == OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            self._state = HALF_OPEN
            self._probes = 0
            self._note_transition(HALF_OPEN)

    def allow(self) -> None:
        """Gate one call. Raises :class:`CircuitOpenError` when the
        breaker is open (or half-open with its probe quota in flight);
        otherwise returns, reserving a probe slot if half-open."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return
            if self._state == HALF_OPEN:
                if self._probes < self.half_open_max_probes:
                    self._probes += 1
                    return
                remaining = None
            else:
                remaining = max(
                    0.0, self.reset_timeout_s
                    - (self._clock() - self._opened_at))
        self._count("raytpu_breaker_rejected",
                    "calls rejected by an open circuit breaker")
        raise CircuitOpenError(self.peer, open_for_s=remaining)

    def record_success(self) -> None:
        """A reply arrived (even an application error): peer is alive."""
        with self._lock:
            if self._state != CLOSED:
                self._state = CLOSED
                self._note_transition(CLOSED)
            self._failures = 0
            self._probes = 0

    def record_failure(self) -> None:
        """The peer was unreachable or silent for one call."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == HALF_OPEN:
                # The probe failed: back to a full cooldown.
                self._state = OPEN
                self._opened_at = self._clock()
                self._probes = 0
                self._note_transition(OPEN)
                return
            self._failures += 1
            if self._state == CLOSED and \
                    self._failures >= self.failure_threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                self._note_transition(OPEN)

    # Called with the lock held: metric emission must never raise.
    def _note_transition(self, new_state: str) -> None:
        self._count("raytpu_breaker_transitions",
                    "circuit breaker state transitions",
                    extra={"state": new_state})

    def _count(self, name: str, desc: str, extra=None) -> None:
        try:
            tags = {"peer": self.peer or "?"}
            keys = ("peer",)
            if extra:
                tags.update(extra)
                keys = ("peer", "state")
            m = _metric("counter", name, desc, keys)
            if m is not None:
                m.inc(1.0, tags=tags)
        except Exception:
            pass


# Per-peer registry: every component talking to the same address shares
# one failure account, so N callers against a dead peer collectively make
# O(threshold) probes — not N * attempts.
_breakers: Dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(peer: str, **kwargs) -> CircuitBreaker:
    """The process-wide breaker for ``peer`` (created on first use;
    ``kwargs`` only apply then)."""
    with _breakers_lock:
        b = _breakers.get(peer)
        if b is None:
            b = CircuitBreaker(peer=peer, **kwargs)
            _breakers[peer] = b
        return b


def breaker_states() -> Dict[str, str]:
    """Snapshot of every registered breaker's current state, keyed by
    peer (post-mortem dumps record which peers were dark at death)."""
    with _breakers_lock:
        items = list(_breakers.items())
    return {peer: b.state for peer, b in items}


def reset_breakers() -> None:
    """Drop every registered breaker (test isolation)."""
    with _breakers_lock:
        _breakers.clear()
