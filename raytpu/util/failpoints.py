"""Deterministic failpoint injection for chaos testing.

Reference analogue: the ``fail::fail_point!()`` macro family used by
TiKV/etcd (and Ray's own ``RAY_testing_*`` fault hooks): production code
is threaded with *named* failpoints that compile to near-zero no-ops
until a test arms them with an action expression. Armed failpoints can
raise, delay, kill the process, or tell the call site to drop a message
— gated by counts and probabilities so multi-step recovery scenarios
(e.g. "drop the first 3 heartbeats, then behave") are expressed in one
string.

Expression grammar (TiKV ``fail-rs`` style)::

    spec  := term ("->" term)*
    term  := [PCT "%"] [CNT "*"] action
    action := "off" | "drop" | "kill_process"
            | "raise(" EXC [",MSG"] ")" | "delay(" SECONDS ")"

Terms are consumed left to right: a ``CNT*``-gated term fires CNT times
then yields to the next term; a term without a count fires forever.
``PCT%`` gates each evaluation on a *deterministically seeded* RNG
(seed = ``RAYTPU_FAILPOINTS_SEED`` env, default 0) so probabilistic
chaos runs are still reproducible.

Examples::

    failpoints.cfg("wire.send.pre", "1*raise(ConnectionError)")
    failpoints.cfg("head.heartbeat.handle", "drop")
    failpoints.cfg("worker.task.run", "1*kill_process")
    failpoints.cfg("node.heartbeat.emit", "3*drop->off")
    failpoints.cfg("transfer.fetch", "50%raise(OSError)")

Activation channels:

- **Python API** — ``cfg()`` / ``off()`` / ``clear()`` in-process.
- **Env var** — ``RAYTPU_FAILPOINTS="name=spec;name2=spec2"`` parsed at
  import, so worker/node subprocesses (which inherit ``os.environ``)
  arm themselves; ``cfg(..., env=True)`` additionally exports the spec
  so processes spawned *after* the call inherit it.
- **Head RPC** — ``failpoint_cfg`` / ``failpoint_clear`` /
  ``failpoint_stat`` handlers on head and node daemons (see
  ``cluster/head.py``, ``cluster/node.py``) let tests arm failpoints on
  already-running remote processes.

Call sites do::

    act = failpoint("wire.send.pre")
    if act is DROP:
        return  # swallow the message

``failpoint()`` raises / sleeps / kills internally; the only return
values are ``None`` (no-op) and the ``DROP`` sentinel for sites that
support swallowing a message.

Every evaluation and fire is counted (``stat()``), so chaos tests can
assert "the failpoint fired exactly N times" instead of sleeping and
hoping.
"""

from __future__ import annotations

import os
import random
import re
import signal
import threading
import time
from typing import Dict, List, Optional

ENV_VAR = "RAYTPU_FAILPOINTS"
SEED_ENV_VAR = "RAYTPU_FAILPOINTS_SEED"


class DROP:  # sentinel: call site should swallow the message
    """Returned by :func:`failpoint` when a ``drop`` action fires."""

    def __init__(self):  # pragma: no cover - never instantiated
        raise TypeError("DROP is a sentinel, not a class to instantiate")


class FailpointError(ValueError):
    """Malformed failpoint spec."""


_TERM_RE = re.compile(
    r"^(?:(?P<pct>\d+(?:\.\d+)?)%)?"
    r"(?:(?P<cnt>\d+)\*)?"
    r"(?P<action>[a-z_]+)"
    r"(?:\((?P<args>[^)]*)\))?$"
)

_ACTIONS = ("off", "drop", "kill_process", "raise", "delay")


def _resolve_exc(name: str):
    """Exception class by name: builtins, then raytpu.core.errors."""
    import builtins

    cls = getattr(builtins, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        return cls
    try:
        from raytpu.core import errors as _errors

        cls = getattr(_errors, name, None)
        if isinstance(cls, type) and issubclass(cls, BaseException):
            return cls
    except Exception:  # pragma: no cover - errors module always imports
        pass
    raise FailpointError(f"unknown exception class {name!r} "
                         "(must be a builtin or raytpu.core.errors name)")


class _Term:
    __slots__ = ("pct", "remaining", "action", "arg", "text")

    def __init__(self, text: str):
        m = _TERM_RE.match(text.strip())
        if m is None:
            raise FailpointError(f"bad failpoint term {text!r}")
        self.text = text.strip()
        self.pct = float(m.group("pct")) / 100.0 if m.group("pct") else None
        self.remaining = int(m.group("cnt")) if m.group("cnt") else None
        self.action = m.group("action")
        if self.action not in _ACTIONS:
            raise FailpointError(
                f"unknown failpoint action {self.action!r} "
                f"(expected one of {_ACTIONS})")
        args = (m.group("args") or "").strip()
        if self.action == "raise":
            if not args:
                raise FailpointError("raise() needs an exception class name")
            parts = [p.strip() for p in args.split(",", 1)]
            self.arg = (_resolve_exc(parts[0]),
                        parts[1] if len(parts) > 1 else None)
        elif self.action == "delay":
            if not args:
                raise FailpointError("delay() needs seconds")
            try:
                self.arg = float(args)
            except ValueError:
                raise FailpointError(
                    f"delay() needs numeric seconds, got {args!r}") from None
            if self.arg < 0:
                raise FailpointError("delay() seconds must be >= 0")
        else:
            if args:
                raise FailpointError(
                    f"action {self.action!r} takes no arguments")
            self.arg = None


class _Failpoint:
    __slots__ = ("name", "spec", "terms", "hits", "fires", "_rng", "_lock")

    def __init__(self, name: str, spec: str):
        terms = [_Term(t) for t in spec.split("->")]
        if not terms:
            raise FailpointError("empty failpoint spec")
        self.name = name
        self.spec = spec
        self.terms = terms
        self.hits = 0
        self.fires = 0
        # Deterministic per-failpoint RNG: probability gates reproduce
        # exactly across runs for a fixed seed.
        seed = int(os.environ.get(SEED_ENV_VAR, "0") or "0")
        self._rng = random.Random(f"{seed}:{name}")
        self._lock = threading.Lock()

    def trigger(self):
        """Evaluate the failpoint once. Executes the current term's
        action (raise / sleep / kill) or returns DROP / None."""
        with self._lock:
            self.hits += 1
            term = self.terms[0] if self.terms else None
            if term is None:
                return None
            if term.pct is not None and self._rng.random() >= term.pct:
                return None  # probability gate: skipped, count not consumed
            if term.remaining is not None:
                term.remaining -= 1
                if term.remaining <= 0:
                    self.terms.pop(0)
            if term.action == "off":
                return None
            self.fires += 1
            action, arg = term.action, term.arg
        # Execute outside the lock: delay must not serialize other
        # threads' evaluations, and raise must not poison the lock.
        if action == "drop":
            return DROP
        if action == "raise":
            exc_cls, msg = arg
            raise exc_cls(msg if msg is not None
                          else f"failpoint {self.name!r} fired")
        if action == "delay":
            time.sleep(arg)
            return None
        if action == "kill_process":
            # SIGKILL, like a real crash: no cleanup, no atexit — the
            # exact signal a chaos test wants to survive.
            os.kill(os.getpid(), signal.SIGKILL)
        return None


# Process-local registry. The hot path reads only this dict: when it is
# empty (the production state) failpoint() is a function call plus one
# truthiness check. Mutation goes through _REG_LOCK.
_REG: Dict[str, _Failpoint] = {}
_REG_LOCK = threading.Lock()


def failpoint(name: str):
    """Evaluate the named failpoint. Near-zero-cost no-op (one empty-dict
    check) when nothing is armed. Returns ``DROP`` when a drop action
    fires, else ``None``; raise/delay/kill happen internally."""
    if not _REG:
        return None
    fp = _REG.get(name)
    if fp is None:
        return None
    return fp.trigger()


def cfg(name: str, spec: str, env: bool = False) -> None:
    """Arm (or re-arm) a failpoint with an action expression.

    ``env=True`` additionally exports the registry to the
    ``RAYTPU_FAILPOINTS`` env var so subprocesses spawned afterwards
    (workers, cluster nodes) inherit the armed state.
    """
    fp = _Failpoint(name, spec)  # validate before mutating the registry
    with _REG_LOCK:
        _REG[name] = fp
    if env:
        _export_env()


def off(name: str, env: bool = False) -> None:
    """Disarm a single failpoint (no-op if it isn't armed)."""
    with _REG_LOCK:
        _REG.pop(name, None)
    if env:
        _export_env()


def clear(env: bool = True) -> None:
    """Disarm every failpoint and (by default) scrub the env var so no
    later subprocess inherits stale chaos state."""
    with _REG_LOCK:
        _REG.clear()
    if env:
        os.environ.pop(ENV_VAR, None)


def active() -> Dict[str, str]:
    """Currently armed failpoints: ``{name: original spec}``."""
    with _REG_LOCK:
        return {name: fp.spec for name, fp in _REG.items()}


def stat(name: str) -> Optional[dict]:
    """Counters for one failpoint: ``{"spec", "hits", "fires",
    "exhausted"}`` — or None if it was never armed (or already cleared).

    ``hits`` counts evaluations, ``fires`` counts actions actually
    taken; ``exhausted`` is True once every count-gated term is spent.
    Chaos tests assert on these instead of sleeping and hoping.
    """
    fp = _REG.get(name)
    if fp is None:
        return None
    with fp._lock:
        return {"spec": fp.spec, "hits": fp.hits, "fires": fp.fires,
                "exhausted": not fp.terms}


def stats() -> Dict[str, dict]:
    """``stat()`` for every armed failpoint."""
    with _REG_LOCK:
        names = list(_REG)
    out = {}
    for n in names:
        s = stat(n)
        if s is not None:
            out[n] = s
    return out


def wait_fired(name: str, times: int = 1, timeout: float = 10.0) -> bool:
    """Block until the named failpoint has fired >= ``times`` (bounded
    poll; returns False on timeout). Lets tests synchronize on 'the
    fault has actually been injected' instead of sleeping a guess."""
    deadline = time.monotonic() + timeout
    while True:
        s = stat(name)
        if s is not None and s["fires"] >= times:
            return True
        if time.monotonic() >= deadline:
            return False
        time.sleep(0.01)


# -- env propagation --------------------------------------------------------


def _export_env() -> None:
    with _REG_LOCK:
        specs = {name: fp.spec for name, fp in _REG.items()}
    if specs:
        os.environ[ENV_VAR] = ";".join(
            f"{n}={s}" for n, s in sorted(specs.items()))
    else:
        os.environ.pop(ENV_VAR, None)


def parse_env(value: str) -> Dict[str, str]:
    """Parse ``name=spec;name2=spec2`` (whitespace-tolerant)."""
    out: Dict[str, str] = {}
    for part in value.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise FailpointError(
                f"bad {ENV_VAR} entry {part!r} (expected name=spec)")
        name, spec = part.split("=", 1)
        out[name.strip()] = spec.strip()
    return out


def load_env(value: Optional[str] = None) -> List[str]:
    """Arm failpoints from ``RAYTPU_FAILPOINTS`` (or an explicit
    string). Called once at import; safe to call again after mutating
    the env var. Returns the names armed."""
    raw = os.environ.get(ENV_VAR, "") if value is None else value
    if not raw:
        return []
    names = []
    for name, spec in parse_env(raw).items():
        cfg(name, spec)
        names.append(name)
    return names


# Subprocesses (workers via WorkerPool._spawn, nodes via cluster_utils)
# inherit os.environ — arming happens here, at first import.
load_env()
