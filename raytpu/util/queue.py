"""Distributed queue backed by an actor (reference:
``python/ray/util/queue.py``)."""

from __future__ import annotations

import time
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


def _make_queue_actor(maxsize: int):
    import raytpu

    @raytpu.remote(num_cpus=0)
    class _QueueActor:
        def __init__(self, maxsize: int):
            import collections

            self._maxsize = maxsize
            self._q = collections.deque()

        def put(self, item) -> bool:
            if self._maxsize > 0 and len(self._q) >= self._maxsize:
                return False
            self._q.append(item)
            return True

        def get(self):
            if not self._q:
                return False, None
            return True, self._q.popleft()

        def qsize(self) -> int:
            return len(self._q)

        def empty(self) -> bool:
            return not self._q

        def full(self) -> bool:
            return self._maxsize > 0 and len(self._q) >= self._maxsize

    return _QueueActor.remote(maxsize)


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        self._actor = _make_queue_actor(maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        import raytpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok = raytpu.get(self._actor.put.remote(item))
            if ok:
                return
            if not block:
                raise Full()
            if deadline is not None and time.monotonic() >= deadline:
                raise Full()
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        import raytpu

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = raytpu.get(self._actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty()
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty()
            time.sleep(0.01)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        import raytpu

        return raytpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        import raytpu

        return raytpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        import raytpu

        return raytpu.get(self._actor.full.remote())

    def put_batch(self, items: List[Any]) -> None:
        for item in items:
            self.put(item)

    def shutdown(self) -> None:
        import raytpu

        raytpu.kill(self._actor)
