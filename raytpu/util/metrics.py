"""User-defined metrics: Counter / Gauge / Histogram.

Reference analogue: ``python/ray/util/metrics.py:137,262,187`` — the
user-facing metric API whose samples flow to Prometheus. The reference
routes through OpenCensus + a per-node metrics agent; we register directly
with ``prometheus_client`` (in-process registry) and expose the scrape
endpoint via :func:`start_metrics_server` — one fewer hop, same exposition
format. Without ``prometheus_client`` installed, metrics degrade to
in-memory counters (observable via ``.value``/tests, nothing exported).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

try:
    import prometheus_client as _prom
except ImportError:  # pragma: no cover - baked into this image
    _prom = None

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0, 30.0, 60.0)
_registry_lock = threading.Lock()
_registered: Dict[str, object] = {}


def _sanitize(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


class _Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        self._name = _sanitize(name)
        self._description = description
        self._tag_keys: Tuple[str, ...] = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        self._prom = self._make_prom() if _prom is not None else None

    def _make_prom(self):
        raise NotImplementedError

    def _signature(self) -> tuple:
        return (type(self).__name__, self._tag_keys)

    def _get_or_register(self, factory):
        with _registry_lock:
            existing = _registered.get(self._name)
            if existing is not None:
                prev_sig, collector = existing
                if prev_sig != self._signature():
                    raise ValueError(
                        f"metric {self._name!r} already registered with a "
                        f"different type/tag_keys: {prev_sig} vs "
                        f"{self._signature()}")
                return collector
            m = factory()
            _registered[self._name] = (self._signature(), m)
            return m

    def set_default_tags(self, tags: Dict[str, str]) -> "_Metric":
        unknown = set(tags) - set(self._tag_keys)
        if unknown:
            raise ValueError(f"unknown tag keys: {sorted(unknown)}")
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        missing = set(self._tag_keys) - set(merged)
        if missing:
            raise ValueError(f"missing tag values for {sorted(missing)}")
        return tuple(merged[k] for k in self._tag_keys)

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys}


class Counter(_Metric):
    """Monotonic counter (reference: ``ray.util.metrics.Counter``)."""

    def _make_prom(self):
        return self._get_or_register(lambda: _prom.Counter(
            self._name, self._description or self._name,
            labelnames=self._tag_keys))

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        if self._prom is not None:
            (self._prom.labels(*key) if key else self._prom).inc(value)

    @property
    def value(self) -> float:
        with self._lock:
            return sum(self._values.values())


class Gauge(_Metric):
    """Point-in-time value (reference: ``ray.util.metrics.Gauge``)."""

    def _make_prom(self):
        return self._get_or_register(lambda: _prom.Gauge(
            self._name, self._description or self._name,
            labelnames=self._tag_keys))

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            self._values[key] = value
        if self._prom is not None:
            (self._prom.labels(*key) if key else self._prom).set(value)

    @property
    def value(self) -> float:
        """The untagged value when one was set; otherwise the most
        recently introduced tag set's value (legacy behavior, only
        deterministic for single-tag-set gauges)."""
        with self._lock:
            if () in self._values:
                return self._values[()]
            vals = list(self._values.values())
            return vals[-1] if vals else 0.0

    @property
    def values(self) -> Dict[Tuple, float]:
        """Per-tag-tuple snapshot (keys ordered by ``tag_keys``)."""
        with self._lock:
            return dict(self._values)


class Histogram(_Metric):
    """Bucketed distribution (reference: ``ray.util.metrics.Histogram``)."""

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        self._boundaries = tuple(boundaries or _DEFAULT_BUCKETS)
        super().__init__(name, description, tag_keys)
        self._observations: List[float] = []
        self._by_key: Dict[Tuple, List[float]] = {}

    def _signature(self) -> tuple:
        return (type(self).__name__, self._tag_keys, self._boundaries)

    def _make_prom(self):
        return self._get_or_register(lambda: _prom.Histogram(
            self._name, self._description or self._name,
            labelnames=self._tag_keys, buckets=self._boundaries))

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            self._observations.append(value)
            self._by_key.setdefault(key, []).append(value)
        if self._prom is not None:
            (self._prom.labels(*key) if key else self._prom).observe(value)

    @property
    def observations(self) -> List[float]:
        """All observations in arrival order (tag-blind, backward
        compatible); per-tag series live in :attr:`observations_by_tag`."""
        with self._lock:
            return list(self._observations)

    @property
    def observations_by_tag(self) -> Dict[Tuple, List[float]]:
        """Observations keyed by tag tuple (ordered by ``tag_keys``)."""
        with self._lock:
            return {k: list(v) for k, v in self._by_key.items()}


_servers: Dict[int, tuple] = {}  # port -> (wsgi_server, thread)
_server_lock = threading.Lock()


def start_metrics_server(port: int = 8090) -> bool:
    """Expose the Prometheus scrape endpoint (reference: per-node metrics
    agent → Prometheus exposition). Idempotent per port; a second caller
    asking for a DIFFERENT port gets its own endpoint (a restarted head
    with a new config must not silently reuse the dead one's port)."""
    if _prom is None:
        return False
    with _server_lock:
        if port in _servers:
            return True
        _servers[port] = _prom.start_http_server(port)
        return True


def stop_metrics_server(port: int) -> None:
    """Shut down the scrape endpoint on ``port`` (no-op if not running)."""
    with _server_lock:
        entry = _servers.pop(port, None)
    if entry is None:
        return
    server, thread = entry
    try:
        server.shutdown()
        # shutdown() only stops the serve loop; the listening socket
        # stays bound until closed — a restart on the same port would
        # otherwise race GC for EADDRINUSE.
        server.server_close()
        thread.join(timeout=5)
    except Exception:  # pragma: no cover
        pass
