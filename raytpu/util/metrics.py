"""User-defined metrics: Counter / Gauge / Histogram — plus the
cluster shipping pipeline.

Reference analogue: ``python/ray/util/metrics.py:137,262,187`` — the
user-facing metric API whose samples flow to Prometheus. The reference
routes through OpenCensus + a per-node metrics agent; we register directly
with ``prometheus_client`` (in-process registry) and expose the scrape
endpoint via :func:`start_metrics_server` — one fewer hop, same exposition
format. Without ``prometheus_client`` installed, metrics degrade to
in-memory counters (observable via ``.value``/tests, nothing exported).

Cluster shipping (reference: ``src/ray/stats/metric_exporter.h:36`` —
per-process collectors drained to a cluster aggregation point): every
process periodically snapshots its registry *deltas* (counter increments,
gauge last-values, histogram bucket increments) into primitive-only
frames that ride the existing liveness paths (node heartbeat,
worker→node notify) to the head's :class:`raytpu.util.tsdb.MetricStore`.
Same bounded-buffer / requeue-on-failure contract as task-event shipping
(``util/task_events.py``). ``RAYTPU_METRICS_SHIP=0`` turns the whole
pipeline off; disabled-and-idle cost at each ship site is a single flag
check (:func:`enabled`).

Tag-cardinality bound: each metric holds at most ``_MAX_SERIES``
(``RAYTPU_METRIC_MAX_SERIES``) distinct tag-sets; overflow folds into a
``{"tag": "<other>"}`` series and bumps
``raytpu_metrics_series_dropped_total`` so a tag explosion can't bloat
the shipping frames or the head store.

Every built-in metric name must be declared in the append-only
:data:`DECLARED_METRICS` table (lint rule RTP015, mirroring the
``declare_env`` registry); user code outside ``raytpu/`` may mint
ad-hoc names freely.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
import weakref
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

try:
    import prometheus_client as _prom
except ImportError:  # pragma: no cover - baked into this image
    _prom = None

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0, 30.0, 60.0)
_registry_lock = threading.Lock()
_registered: Dict[str, object] = {}
_instances: "weakref.WeakSet[_Metric]" = weakref.WeakSet()

# Append-only registry of every metric name the runtime itself constructs
# (lint rule RTP015 walks Counter/Gauge/Histogram call sites under
# ``raytpu/`` and cross-checks against this table, exactly like RTP008
# does for env vars). Keep alphabetized within each section; never
# remove an entry — renames append the new name and leave the old one.
DECLARED_METRICS: Dict[str, str] = {
    # -- head / cluster state ------------------------------------------
    "raytpu_actors": "live actor count by state",
    "raytpu_cluster_nodes": "cluster node count by liveness state",
    "raytpu_placement_groups": "placement group count",
    "raytpu_resources_available": "available resource units by kind",
    "raytpu_resources_total": "total resource units by kind",
    "raytpu_schedule_requests_total": "scheduling requests handled",
    "raytpu_tasks_done_total": "tasks finished cluster-wide",
    "raytpu_tasks_submitted_total": "task specs accepted for scheduling",
    "raytpu_tenant_preempted_total": "running tasks preempted per tenant",
    "raytpu_tenant_queued": "specs queued at the head per tenant",
    "raytpu_tenant_tasks_placed_total": "placements per tenant",
    "raytpu_tenant_throttled_total": "admission-shed submissions per tenant",
    # -- inference serving ---------------------------------------------
    "raytpu_infer_decode_mfu": "model FLOPs utilization per decode step",
    "raytpu_infer_decode_tokens_per_s": "decode throughput",
    "raytpu_infer_decode_tokens_total": "decode tokens generated",
    "raytpu_infer_handoff_aborts_total":
        "KV handoffs aborted mid-stream (peer death, TTL sweep)",
    "raytpu_infer_handoff_bytes_total":
        "payload bytes streamed in cross-replica KV handoffs",
    "raytpu_infer_handoff_fallbacks_total":
        "disaggregated pulls that fell back to a local prefill",
    "raytpu_infer_handoff_pages_total":
        "KV pages grafted via disaggregated prefill->decode handoff",
    "raytpu_infer_kv_page_utilization": "KV page pool utilization 0..1",
    "raytpu_infer_prefill_tokens_per_s": "prefill throughput",
    "raytpu_infer_prefill_tokens_total": "prefill tokens processed",
    "raytpu_infer_prefix_evictions_total": "prefix cache evictions",
    "raytpu_infer_prefix_hit_tokens_total": "prefix cache tokens reused",
    "raytpu_infer_prefix_hits_total": "prefix cache lookup hits",
    "raytpu_infer_prefix_lookups_total": "prefix cache lookups",
    "raytpu_infer_running_requests": "requests in the running batch",
    "raytpu_infer_step_seconds": "decode step wall time",
    "raytpu_infer_ttft_seconds": "time-to-first-token distribution",
    "raytpu_infer_waiting_requests": "requests queued for admission",
    # -- node daemon ---------------------------------------------------
    "raytpu_node_pending_tasks": "tasks queued on the node",
    "raytpu_node_pull_bytes_total": "object bytes pulled from peers",
    "raytpu_node_push_rx_bytes_total": "object bytes received via push",
    "raytpu_node_rss_bytes": "node daemon resident set size",
    "raytpu_node_running_tasks": "tasks executing on the node",
    "raytpu_node_shm_capacity_bytes": "shared-memory arena capacity",
    "raytpu_node_shm_used_bytes": "shared-memory arena bytes in use",
    "raytpu_node_shm_used_highwater_bytes":
        "shared-memory arena high-water mark since daemon start",
    # -- continuous profiling / performance attribution ----------------
    "raytpu_hbm_peak_bytes": "device memory high-water mark",
    "raytpu_hbm_used_bytes": "device memory in use",
    "raytpu_rpc_stage_seconds":
        "server dispatch wall time per stage (recv/decode/queue/"
        "handler/encode/send)",
    "raytpu_train_mfu": "model FLOPs utilization per train step",
    "raytpu_train_step_seconds": "train step wall time",
    # -- serve ---------------------------------------------------------
    "raytpu_serve_requests_total":
        "serve requests routed, by deployment and tenant",
    "raytpu_serve_ttft_seconds":
        "request time-to-first-token, by deployment and tenant",
    "raytpu_serve_tpot_seconds":
        "inter-token latency (time per output token)",
    "raytpu_serve_e2e_seconds":
        "request end-to-end latency, by deployment and tenant",
    "raytpu_serve_queue_seconds":
        "replica queue wait (enqueue to semaphore)",
    "raytpu_serve_tokens_delivered_total":
        "tokens streamed to consumers, by deployment and tenant",
    "raytpu_serve_tokens_wasted_total":
        "tokens whose work was discarded, by cause",
    # -- metrics pipeline itself ---------------------------------------
    "raytpu_metrics_series_dropped_total":
        "tag-sets folded into <other> by the cardinality cap",
    # -- worker --------------------------------------------------------
    "raytpu_worker_tasks_total": "tasks executed by the worker process",
}

# Tag-cardinality cap: distinct tag-sets per metric before folding into
# the ``<other>`` series. Module global so tests can patch it.
ENV_MAX_SERIES = "RAYTPU_METRIC_MAX_SERIES"
_MAX_SERIES = int(os.environ.get(ENV_MAX_SERIES, "") or 128)
OTHER_TAG_VALUE = "<other>"

# Reserved headroom past the cap for series carrying a REAL "tenant"
# tag value: per-tenant SLO series (quota throttles, fairness, serve
# latency) must not silently fold into ``<other>`` just because a
# free-form tag family (task names, resources) filled the table first —
# a folded tenant series reads as "tenant is fine" on every dashboard.
ENV_TENANT_RESERVED = "RAYTPU_METRIC_TENANT_RESERVED"
_TENANT_RESERVED = int(os.environ.get(ENV_TENANT_RESERVED, "") or 32)


def _sanitize(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


class _Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        self._name = _sanitize(name)
        self._description = description
        self._tag_keys: Tuple[str, ...] = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[Tuple, float] = {}
        self._lock = threading.Lock()
        self._ship_state: Dict[Tuple, object] = {}
        self._prom = self._make_prom() if _prom is not None else None
        with _registry_lock:
            _instances.add(self)

    def _make_prom(self):
        raise NotImplementedError

    def _signature(self) -> tuple:
        return (type(self).__name__, self._tag_keys)

    def _get_or_register(self, factory):
        with _registry_lock:
            existing = _registered.get(self._name)
            if existing is not None:
                prev_sig, collector = existing
                if prev_sig != self._signature():
                    raise ValueError(
                        f"metric {self._name!r} already registered with a "
                        f"different type/tag_keys: {prev_sig} vs "
                        f"{self._signature()}")
                return collector
            m = factory()
            _registered[self._name] = (self._signature(), m)
            return m

    def set_default_tags(self, tags: Dict[str, str]) -> "_Metric":
        unknown = set(tags) - set(self._tag_keys)
        if unknown:
            raise ValueError(f"unknown tag keys: {sorted(unknown)}")
        self._default_tags = dict(tags)
        return self

    def _tag_tuple(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        missing = set(self._tag_keys) - set(merged)
        if missing:
            raise ValueError(f"missing tag values for {sorted(missing)}")
        return tuple(merged[k] for k in self._tag_keys)

    def _fold(self, key: Tuple, table: Dict) -> Tuple[Tuple, bool]:
        """Cardinality cap (caller holds ``self._lock``): a key beyond
        ``_MAX_SERIES`` distinct tag-sets folds into the ``<other>``
        series so one runaway tag can't bloat frames or the head store.
        Keys whose "tenant" tag carries a real value get the reserved
        headroom (``_TENANT_RESERVED``) before folding — tenant series
        are the isolation story's evidence and must outlive free-form
        tag churn. Every fold still counts in
        ``raytpu_metrics_series_dropped_total`` tagged with the metric
        name, so the evicted family is named, never silent."""
        if not self._tag_keys or key in table or len(table) < _MAX_SERIES:
            return key, False
        if "tenant" in self._tag_keys and \
                len(table) < _MAX_SERIES + _TENANT_RESERVED:
            tv = key[self._tag_keys.index("tenant")]
            if tv and tv != OTHER_TAG_VALUE:
                return key, False
        return (OTHER_TAG_VALUE,) * len(self._tag_keys), True

    def _delta_rows(self) -> List[list]:
        raise NotImplementedError

    @property
    def info(self) -> dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys}


class Counter(_Metric):
    """Monotonic counter (reference: ``ray.util.metrics.Counter``)."""

    def _make_prom(self):
        return self._get_or_register(lambda: _prom.Counter(
            self._name, self._description or self._name,
            labelnames=self._tag_keys))

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        key = self._tag_tuple(tags)
        with self._lock:
            key, folded = self._fold(key, self._values)
            self._values[key] = self._values.get(key, 0.0) + value
        if folded:
            _note_series_drop(self._name)
        if self._prom is not None:
            (self._prom.labels(*key) if key else self._prom).inc(value)

    @property
    def value(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def _delta_rows(self) -> List[list]:
        rows: List[list] = []
        with self._lock:
            for key, val in self._values.items():
                inc = val - self._ship_state.get(key, 0.0)
                if inc > 0:
                    rows.append(["c", self._name, list(self._tag_keys),
                                 list(key), inc])
                    self._ship_state[key] = val
        return rows


class Gauge(_Metric):
    """Point-in-time value (reference: ``ray.util.metrics.Gauge``)."""

    def _make_prom(self):
        return self._get_or_register(lambda: _prom.Gauge(
            self._name, self._description or self._name,
            labelnames=self._tag_keys))

    def set(self, value: float,
            tags: Optional[Dict[str, str]] = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            key, folded = self._fold(key, self._values)
            self._values[key] = value
        if folded:
            _note_series_drop(self._name)
        if self._prom is not None:
            (self._prom.labels(*key) if key else self._prom).set(value)

    @property
    def value(self) -> float:
        """The untagged value when one was set; otherwise the most
        recently introduced tag set's value (legacy behavior, only
        deterministic for single-tag-set gauges)."""
        with self._lock:
            if () in self._values:
                return self._values[()]
            vals = list(self._values.values())
            return vals[-1] if vals else 0.0

    @property
    def values(self) -> Dict[Tuple, float]:
        """Per-tag-tuple snapshot (keys ordered by ``tag_keys``)."""
        with self._lock:
            return dict(self._values)

    def _delta_rows(self) -> List[list]:
        # Gauges ship every live tag-set each interval (not just on
        # change) so steady values still produce points — a flat-lined
        # KV-utilization gauge must not read as a vanished series.
        with self._lock:
            return [["g", self._name, list(self._tag_keys), list(key), val]
                    for key, val in self._values.items()]


class Histogram(_Metric):
    """Bucketed distribution (reference: ``ray.util.metrics.Histogram``)."""

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[Sequence[float]] = None,
                 tag_keys: Optional[Sequence[str]] = None):
        self._boundaries = tuple(boundaries or _DEFAULT_BUCKETS)
        super().__init__(name, description, tag_keys)
        self._observations: List[float] = []
        self._by_key: Dict[Tuple, List[float]] = {}

    def _signature(self) -> tuple:
        return (type(self).__name__, self._tag_keys, self._boundaries)

    def _make_prom(self):
        return self._get_or_register(lambda: _prom.Histogram(
            self._name, self._description or self._name,
            labelnames=self._tag_keys, buckets=self._boundaries))

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = self._tag_tuple(tags)
        with self._lock:
            key, folded = self._fold(key, self._by_key)
            self._observations.append(value)
            self._by_key.setdefault(key, []).append(value)
        if folded:
            _note_series_drop(self._name)
        if self._prom is not None:
            (self._prom.labels(*key) if key else self._prom).observe(value)

    @property
    def observations(self) -> List[float]:
        """All observations in arrival order (tag-blind, backward
        compatible); per-tag series live in :attr:`observations_by_tag`."""
        with self._lock:
            return list(self._observations)

    @property
    def observations_by_tag(self) -> Dict[Tuple, List[float]]:
        """Observations keyed by tag tuple (ordered by ``tag_keys``)."""
        with self._lock:
            return {k: list(v) for k, v in self._by_key.items()}

    def _delta_rows(self) -> List[list]:
        rows: List[list] = []
        with self._lock:
            for key, obs in self._by_key.items():
                idx = self._ship_state.get(key, 0)
                new = obs[idx:]
                if not new:
                    continue
                counts = [0] * (len(self._boundaries) + 1)
                for v in new:
                    counts[bisect.bisect_left(self._boundaries, v)] += 1
                rows.append(["h", self._name, list(self._tag_keys),
                             list(key), list(self._boundaries), counts,
                             float(sum(new)), len(new)])
                self._ship_state[key] = len(obs)
        return rows


# The fold counter is created lazily (the class must exist first) and
# never reports on itself: its own key space is bounded by the set of
# metric names, but self-reporting could recurse through ``inc``.
_series_dropped: Optional[Counter] = None
_series_dropped_lock = threading.Lock()


def _note_series_drop(metric_name: str) -> None:
    global _series_dropped
    if metric_name == "raytpu_metrics_series_dropped_total":
        return
    with _series_dropped_lock:
        if _series_dropped is None:
            _series_dropped = Counter(
                "raytpu_metrics_series_dropped_total",
                "tag-sets folded into <other> by the cardinality cap",
                tag_keys=("metric",))
    try:
        _series_dropped.inc(tags={"metric": metric_name})
    except Exception:  # pragma: no cover - never break the caller
        pass


# ---------------------------------------------------------------------------
# Cluster shipping: registry deltas -> primitive frames -> head TSDB.
#
# Frame shape (strict-wire primitives only):
#   [proc_id, seq, ts, rows]
# with rows one of
#   ["c", name, [tag_keys], [tag_vals], increment]
#   ["g", name, [tag_keys], [tag_vals], last_value]
#   ["h", name, [tag_keys], [tag_vals], [boundaries], [bucket_incs],
#    sum_inc, count_inc]
# ``seq`` is per-origin monotonic; the head drops seq <= last-applied so
# a requeued-and-reshipped frame merges idempotently.
# ---------------------------------------------------------------------------

ENV_SHIP = "RAYTPU_METRICS_SHIP"
ENV_BUFFER_MAX = "RAYTPU_METRICS_BUFFER_MAX"

_BUFFER_MAX = int(os.environ.get(ENV_BUFFER_MAX, "") or 256)
_ship_enabled = os.environ.get(ENV_SHIP, "") not in ("0", "false", "False")
_ship_lock = threading.Lock()
_frames: Deque[list] = deque()
_frames_dropped_total = 0
_frames_dropped_shipped = 0  # watermark: drops already reported downstream
_ship_seq = 0
_last_collect = [0.0]
_proc_id = [""]


def enabled() -> bool:
    """THE flag check: every ship site guards with exactly this call, so
    ``RAYTPU_METRICS_SHIP=0`` costs one boolean read per tick."""
    return _ship_enabled


def enable_metrics_ship(env: bool = False) -> None:
    global _ship_enabled
    _ship_enabled = True
    if env:
        os.environ[ENV_SHIP] = "1"


def disable_metrics_ship(env: bool = False) -> None:
    """Default is ON, so (unlike task events) disabling for children
    must *set* the env var to ``0`` rather than unset it."""
    global _ship_enabled
    _ship_enabled = False
    if env:
        os.environ[ENV_SHIP] = "0"


def set_shipper_identity(proc_id: str) -> None:
    """Stamp outgoing frames with this process's stable identity
    (``head`` / ``node:<hex12>`` / ``driver:<hex12>`` /
    ``worker:<nodehex12>.<workerhex12>``). The head tombstones dead
    procs by this id, so the convention is load-bearing."""
    _proc_id[0] = str(proc_id)


def shipper_identity() -> str:
    return _proc_id[0] or f"pid:{os.getpid()}"


def collect(min_interval_s: float = 0.0, force: bool = False,
            now: Optional[float] = None) -> bool:
    """Snapshot registry deltas into one pending frame. Rate-limited by
    ``min_interval_s`` so a fast heartbeat loop can call it every beat.
    Returns True iff a frame was produced."""
    if not _ship_enabled:
        return False
    if now is None:
        now = time.time()
    with _ship_lock:
        if not force and min_interval_s > 0 and \
                now - _last_collect[0] < min_interval_s:
            return False
        _last_collect[0] = now
    with _registry_lock:
        insts = list(_instances)
    rows: List[list] = []
    for m in insts:
        try:
            rows.extend(m._delta_rows())
        except Exception:  # pragma: no cover - one bad metric != no ship
            pass
    if not rows:
        return False
    global _ship_seq, _frames_dropped_total
    with _ship_lock:
        _ship_seq += 1
        frame = [shipper_identity(), _ship_seq, now, rows]
        if len(_frames) >= _BUFFER_MAX:
            _frames.popleft()
            _frames_dropped_total += 1
        _frames.append(frame)
    return True


def drain() -> Tuple[List[list], int]:
    """Take everything pending plus the not-yet-reported drop delta.
    On ship failure hand both back via :func:`requeue` — the watermark
    arithmetic keeps drop counts exact across retries."""
    global _frames_dropped_shipped
    with _ship_lock:
        frames = list(_frames)
        _frames.clear()
        dropped_delta = _frames_dropped_total - _frames_dropped_shipped
        _frames_dropped_shipped = _frames_dropped_total
    return frames, dropped_delta


def requeue(frames: List[list], dropped: int = 0) -> None:
    """Put a failed ship back at the FRONT of the buffer (oldest-first
    order preserved); overflow drops the oldest of the requeued batch."""
    if not frames and not dropped:
        return
    global _frames_dropped_total, _frames_dropped_shipped
    with _ship_lock:
        _frames_dropped_shipped -= dropped
        space = _BUFFER_MAX - len(_frames)
        if len(frames) > space:
            lost = len(frames) - max(space, 0)
            frames = frames[lost:]
            _frames_dropped_total += lost
        _frames.extendleft(reversed(frames))


def ingest(frames: List[list], dropped: int = 0) -> None:
    """Relay path: a node daemon absorbs a worker's drained frames into
    its own buffer; they ride the next heartbeat to the head."""
    global _frames_dropped_total
    with _ship_lock:
        _frames_dropped_total += int(dropped or 0)
        for f in frames or ():
            if len(_frames) >= _BUFFER_MAX:
                _frames.popleft()
                _frames_dropped_total += 1
            _frames.append(f)


def pending_frames() -> int:
    with _ship_lock:
        return len(_frames)


def reset_shipping() -> None:
    """Test isolation: clear the buffer, counters, and every metric's
    per-instance ship watermarks (so totals re-ship as fresh deltas)."""
    global _frames_dropped_total, _frames_dropped_shipped, _ship_seq
    with _ship_lock:
        _frames.clear()
        _frames_dropped_total = 0
        _frames_dropped_shipped = 0
        _ship_seq = 0
        _last_collect[0] = 0.0
    with _registry_lock:
        insts = list(_instances)
    for m in insts:
        with m._lock:
            m._ship_state.clear()


_servers: Dict[int, tuple] = {}  # port -> (wsgi_server, thread)
_server_lock = threading.Lock()


def start_metrics_server(port: int = 8090) -> bool:
    """Expose the Prometheus scrape endpoint (reference: per-node metrics
    agent → Prometheus exposition). Idempotent per port; a second caller
    asking for a DIFFERENT port gets its own endpoint (a restarted head
    with a new config must not silently reuse the dead one's port)."""
    if _prom is None:
        return False
    with _server_lock:
        if port in _servers:
            return True
        _servers[port] = _prom.start_http_server(port)
        return True


def stop_metrics_server(port: int) -> None:
    """Shut down the scrape endpoint on ``port`` (no-op if not running)."""
    with _server_lock:
        entry = _servers.pop(port, None)
    if entry is None:
        return
    server, thread = entry
    try:
        server.shutdown()
        # shutdown() only stops the serve loop; the listening socket
        # stays bound until closed — a restart on the same port would
        # otherwise race GC for EADDRINUSE.
        server.server_close()
        thread.join(timeout=5)
    except Exception:  # pragma: no cover
        pass
