"""Head-side bounded time-series store and SLO alert evaluator.

Reference analogue: the reference cluster routes per-process OpenCensus
samples through a metrics agent to exporters (``src/ray/stats/
metric_exporter.h:36``); dashboard/autoscaler consumers then query an
external Prometheus. We keep a small TSDB *inside* the head instead, so
the runtime can answer "what is the cluster doing right now, and what
was it doing 10 minutes ago" without any external scrape
infrastructure.

Bounds (all hard, all enforced here):

- per series a **fine ring** (``fine_slots`` buckets of ``fine_step_s``,
  default 120 x 5 s = 10 min) and a **coarse ring** (``coarse_slots`` x
  ``coarse_step_s``, default 120 x 30 s = 1 h). When a fine slot is
  reused its old bucket *folds* into the coarse ring (staircase
  downsampling: counters sum, gauges keep the latest value, histogram
  buckets add) — recent history is sharp, old history survives coarse;
- tag-sets are interned (one tuple shared by every series with the same
  tags) and every series carries an implicit ``proc`` tag, which is what
  makes cross-process aggregation a plain group-by;
- a byte-estimate accounting with per-kind FIFO eviction (like
  ``TaskEventStore``) keeps the whole store under ``max_bytes``;
- per-origin ``seq`` dedup makes delta pushes idempotent: a frame
  requeued by a flaky heartbeat and shipped twice applies once;
- dead processes are tombstoned (:meth:`mark_proc_dead`): their series
  drop and late frames from them are rejected, so a node death can't
  resurrect stale series.

The store is clock-injectable (``clock=``) so ring/downsample/eviction
invariants are testable under a fake clock.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

QUANTILE_AGGS = {"p50": 0.50, "p90": 0.90, "p95": 0.95, "p99": 0.99}
AGGS = ("sum", "max", "min", "avg", "rate") + tuple(QUANTILE_AGGS)


class _Series:
    __slots__ = ("kind", "name", "tags", "boundaries", "cost",
                 "fine_ts", "fine_val", "coarse_ts", "coarse_val",
                 "total", "last", "last_ts",
                 "bucket_totals", "sum_total", "count_total")

    def __init__(self, kind: str, name: str, tags: Tuple[Tuple[str, str], ...],
                 fine_slots: int, coarse_slots: int,
                 boundaries: Optional[Tuple[float, ...]] = None):
        self.kind = kind
        self.name = name
        self.tags = tags
        self.boundaries = boundaries
        self.fine_ts = [0.0] * fine_slots
        self.fine_val: List[object] = [None] * fine_slots
        self.coarse_ts = [0.0] * coarse_slots
        self.coarse_val: List[object] = [None] * coarse_slots
        self.total = 0.0          # counters: cumulative sum of increments
        self.last = 0.0           # gauges: most recent value
        self.last_ts = 0.0
        nb = len(boundaries) + 1 if boundaries else 0
        self.bucket_totals = [0] * nb   # histograms: cumulative buckets
        self.sum_total = 0.0
        self.count_total = 0
        slots = fine_slots + coarse_slots
        per_slot = 16 + (nb + 2) * 8 if kind == "h" else 16
        self.cost = 200 + slots * per_slot + \
            sum(len(k) + len(str(v)) for k, v in tags)

    def _zero(self):
        if self.kind == "h":
            nb = len(self.boundaries) + 1
            return [[0] * nb, 0.0, 0]     # [bucket_incs, sum_inc, count_inc]
        return 0.0

    def _merge(self, slot_val, add):
        if self.kind == "g":
            return add                     # latest value wins
        if self.kind == "h":
            counts, s, c = slot_val
            acounts, asum, acount = add
            for i, v in enumerate(acounts):
                counts[i] += v
            return [counts, s + asum, c + acount]
        return slot_val + add              # counter: increments sum


class MetricStore:
    """Bounded in-memory TSDB behind the head's ``metrics_*`` RPCs."""

    def __init__(self, max_bytes: int = 8_000_000,
                 fine_step_s: float = 5.0, fine_slots: int = 120,
                 coarse_step_s: float = 30.0, coarse_slots: int = 120,
                 clock: Callable[[], float] = time.time):
        self.max_bytes = int(max_bytes)
        self.fine_step = float(fine_step_s)
        self.fine_slots = int(fine_slots)
        self.coarse_step = float(coarse_step_s)
        self.coarse_slots = int(coarse_slots)
        self._clock = clock
        self._lock = threading.Lock()
        # (name, interned-tags) -> _Series, insertion-ordered per kind
        # for FIFO eviction.
        self._series: Dict[str, OrderedDict] = {
            "c": OrderedDict(), "g": OrderedDict(), "h": OrderedDict()}
        self._tag_intern: Dict[Tuple, Tuple] = {}
        self._bytes = 0
        self._proc_seq: Dict[str, int] = {}
        self._dead_procs: set = set()      # hex12 node prefixes
        self.frames_applied = 0
        self.frames_deduped = 0
        self.frames_rejected = 0           # tombstoned origin
        self.rows_dropped = 0              # malformed / kind conflict
        self.series_evicted = 0
        self.upstream_drops = 0            # frames lost before reaching us

    # -- ingest ------------------------------------------------------------

    def push(self, frames: List[list]) -> int:
        """Apply shipped delta frames; returns how many frames applied.
        Idempotent per origin: ``seq`` <= last-applied is a duplicate
        (a requeued-and-reshipped frame merges once)."""
        applied = 0
        with self._lock:
            for frame in frames or ():
                try:
                    proc, seq, ts, rows = frame
                    proc = str(proc)
                    seq = int(seq)
                    ts = float(ts)
                except (TypeError, ValueError):
                    self.rows_dropped += 1
                    continue
                if self._proc_dead(proc):
                    self.frames_rejected += 1
                    continue
                if seq <= self._proc_seq.get(proc, 0):
                    self.frames_deduped += 1
                    continue
                self._proc_seq[proc] = seq
                for row in rows:
                    self._apply_row(proc, ts, row)
                applied += 1
                self.frames_applied += 1
        return applied

    def _proc_dead(self, proc: str) -> bool:
        for p in self._dead_procs:
            if proc in (f"node:{p}", f"driver:{p}") or \
                    proc.startswith(f"worker:{p}."):
                return True
        return False

    def _apply_row(self, proc: str, ts: float, row: list) -> None:
        try:
            kind = row[0]
            name = str(row[1])
            keys = [str(k) for k in row[2]]
            vals = [str(v) for v in row[3]]
            if kind == "h":
                boundaries = tuple(float(b) for b in row[4])
                add = [[int(c) for c in row[5]], float(row[6]), int(row[7])]
                if len(add[0]) != len(boundaries) + 1:
                    raise ValueError("bucket count mismatch")
            elif kind in ("c", "g"):
                boundaries = None
                add = float(row[4])
            else:
                raise ValueError(f"unknown row kind {kind!r}")
        except (TypeError, ValueError, IndexError):
            self.rows_dropped += 1
            return
        tags = tuple(sorted({**dict(zip(keys, vals)), "proc": proc}.items()))
        tags = self._tag_intern.setdefault(tags, tags)
        table = self._series[kind]
        s = table.get((name, tags))
        if s is None:
            s = _Series(kind, name, tags, self.fine_slots, self.coarse_slots,
                        boundaries)
            if not self._make_room(kind, s.cost):
                self.rows_dropped += 1
                return
            table[(name, tags)] = s
            self._bytes += s.cost
        elif kind == "h" and s.boundaries != boundaries:
            self.rows_dropped += 1       # boundary change mid-flight
            return
        self._write(s, ts, add)

    def _make_room(self, kind: str, cost: int) -> bool:
        if cost > self.max_bytes:
            return False
        while self._bytes + cost > self.max_bytes:
            # FIFO-evict the oldest series of the same kind first (like
            # TaskEventStore's per-kind bound); fall back to the oldest
            # of any kind so one kind can't wedge the store.
            victim_table = None
            if self._series[kind]:
                victim_table = self._series[kind]
            else:
                for t in self._series.values():
                    if t:
                        victim_table = t
                        break
            if victim_table is None:
                return False
            _, victim = victim_table.popitem(last=False)
            self._bytes -= victim.cost
            self.series_evicted += 1
        return True

    def _write(self, s: _Series, ts: float, add) -> None:
        b = math.floor(ts / self.fine_step) * self.fine_step
        i = int(b / self.fine_step) % self.fine_slots
        if s.fine_ts[i] != b:
            if s.fine_ts[i] > b:
                return                    # older than the live window
            if s.fine_ts[i]:
                self._fold(s, i)
            s.fine_ts[i] = b
            s.fine_val[i] = s._zero()
        s.fine_val[i] = s._merge(s.fine_val[i], add)
        if s.kind == "c":
            s.total += add
        elif s.kind == "g":
            if ts >= s.last_ts:
                s.last, s.last_ts = add, ts
        else:
            for j, v in enumerate(add[0]):
                s.bucket_totals[j] += v
            s.sum_total += add[1]
            s.count_total += add[2]

    def _fold(self, s: _Series, i: int) -> None:
        """Staircase downsample: a reclaimed fine slot merges into the
        coarse ring before it is overwritten."""
        old_b = s.fine_ts[i]
        cb = math.floor(old_b / self.coarse_step) * self.coarse_step
        ci = int(cb / self.coarse_step) % self.coarse_slots
        if s.coarse_ts[ci] != cb:
            if s.coarse_ts[ci] > cb:
                return                    # beyond even the coarse window
            s.coarse_ts[ci] = cb
            s.coarse_val[ci] = s._zero()
        s.coarse_val[ci] = s._merge(s.coarse_val[ci], s.fine_val[i])

    def mark_proc_dead(self, node_hex12: str) -> int:
        """Tombstone every proc rooted at this node (daemon, driver,
        workers): drop their series now and reject any late frame, so a
        died-mid-ship node can't resurrect stale series."""
        p = str(node_hex12)[:12]
        removed = 0
        with self._lock:
            self._dead_procs.add(p)
            for table in self._series.values():
                doomed = [k for k, s in table.items()
                          if self._tags_proc_dead(s.tags, p)]
                for k in doomed:
                    self._bytes -= table[k].cost
                    del table[k]
                    removed += 1
            for proc in [q for q in self._proc_seq if self._proc_dead(q)]:
                del self._proc_seq[proc]
        return removed

    @staticmethod
    def _tags_proc_dead(tags: Tuple, p: str) -> bool:
        proc = dict(tags).get("proc", "")
        return proc in (f"node:{p}", f"driver:{p}") or \
            proc.startswith(f"worker:{p}.")

    def revive_proc(self, node_hex12: str) -> None:
        """A (re-)registered node sheds its tombstone so shipping
        resumes — the head-bounce / node-reconnect path."""
        with self._lock:
            self._dead_procs.discard(str(node_hex12)[:12])

    def seq_state(self) -> Dict:
        """JSON-safe export of the sequencing state a successor head
        needs for correctness: per-origin applied seqs (so re-shipped
        frames dedup instead of double-counting) and proc-death
        tombstones (so a dead origin's late frames stay rejected).
        Series data is intentionally NOT exported — it is lossy-bounded
        telemetry; the seq/tombstone state is what must not regress."""
        with self._lock:
            return {"proc_seq": dict(self._proc_seq),
                    "dead": sorted(self._dead_procs)}

    def restore_seq_state(self, state: Dict) -> None:
        """Merge a shipped :meth:`seq_state` into this store (takeover /
        restart path). Merge, not replace: per-origin seqs keep the MAX
        of both sides and tombstones union, so a restore can only make
        dedup stricter — never resurrect a dead origin or re-admit an
        already-applied frame."""
        if not isinstance(state, dict):
            return
        with self._lock:
            for proc, seq in (state.get("proc_seq") or {}).items():
                try:
                    seq = int(seq)
                except (TypeError, ValueError):
                    continue
                proc = str(proc)
                if seq > self._proc_seq.get(proc, 0):
                    self._proc_seq[proc] = seq
            for p in state.get("dead") or ():
                self._dead_procs.add(str(p)[:12])

    # -- query -------------------------------------------------------------

    def query(self, name: str, tags: Optional[Dict[str, str]] = None,
              agg: str = "sum", since_s: float = 600.0,
              step: Optional[float] = None,
              now: Optional[float] = None) -> Dict:
        """Cross-process aggregation over matching series.

        ``agg``: counters — ``sum`` (increments per bucket), ``rate``
        (increments/s), ``max``/``avg``/``min`` across per-series
        increments; gauges — ``sum``/``max``/``min``/``avg`` across
        series; histograms — ``p50/p90/p95/p99`` from merged buckets,
        ``avg`` from merged sum/count, ``rate`` = observations/s.
        """
        if agg not in AGGS:
            raise ValueError(f"unknown agg {agg!r} (want one of {AGGS})")
        if now is None:
            now = self._clock()
        since = now - float(since_s)
        out_step = float(step) if step else (
            self.fine_step if since_s <= self.fine_step * self.fine_slots
            else self.coarse_step)
        with self._lock:
            matched = [s for table in self._series.values()
                       for s in table.values()
                       if s.name == name and self._tags_match(s.tags, tags)]
            kind = matched[0].kind if matched else None
            per_series = [self._series_points(s, since, out_step)
                          for s in matched]
        points = self._aggregate(kind, per_series, agg, out_step)
        return {"name": name, "kind": kind, "agg": agg, "step": out_step,
                "series_matched": len(matched),
                "points": [[t, v] for t, v in sorted(points.items())]}

    @staticmethod
    def _tags_match(series_tags: Tuple, want: Optional[Dict]) -> bool:
        if not want:
            return True
        d = dict(series_tags)
        return all(d.get(k) == str(v) for k, v in want.items())

    def _series_points(self, s: _Series, since: float,
                       out_step: float) -> Dict[float, object]:
        """One series' buckets regridded to ``out_step``. Coarse and fine
        rings never double-count: a bucket lives in exactly one ring
        (fine until its slot is reclaimed, coarse after folding)."""
        out: Dict[float, object] = {}
        ts_of: Dict[float, float] = {}    # gauges: latest source bucket wins
        for ring_ts, ring_val in ((s.coarse_ts, s.coarse_val),
                                  (s.fine_ts, s.fine_val)):
            for b, v in zip(ring_ts, ring_val):
                if not b or b < since or v is None:
                    continue
                ob = math.floor(b / out_step) * out_step
                if ob not in out:
                    out[ob] = s._zero()
                    ts_of[ob] = -1.0
                if s.kind == "g":
                    if b > ts_of[ob]:
                        out[ob], ts_of[ob] = v, b
                else:
                    out[ob] = s._merge(out[ob], v)
        return out

    def _aggregate(self, kind: Optional[str],
                   per_series: List[Dict[float, object]], agg: str,
                   out_step: float) -> Dict[float, float]:
        merged: Dict[float, list] = {}
        for pts in per_series:
            for t, v in pts.items():
                merged.setdefault(t, []).append(v)
        out: Dict[float, float] = {}
        for t, vals in merged.items():
            if kind == "h":
                counts = [0] * len(vals[0][0])
                hsum, hcount = 0.0, 0
                for c, sm, ct in vals:
                    for i, x in enumerate(c):
                        counts[i] += x
                    hsum += sm
                    hcount += ct
                if agg in QUANTILE_AGGS:
                    boundaries = self._boundaries_for(kind, counts)
                    q = _bucket_quantile(counts, boundaries,
                                         QUANTILE_AGGS[agg])
                    if q is None:
                        continue
                    out[t] = q
                elif agg == "avg":
                    if hcount:
                        out[t] = hsum / hcount
                elif agg == "rate":
                    out[t] = hcount / out_step
                elif agg == "sum":
                    out[t] = hsum
                elif agg == "max":
                    out[t] = max((sm for _, sm, _ in vals), default=0.0)
                else:
                    out[t] = min((sm for _, sm, _ in vals), default=0.0)
            else:
                nums = [float(v) for v in vals]
                if agg == "sum":
                    out[t] = sum(nums)
                elif agg == "rate":
                    out[t] = sum(nums) / out_step
                elif agg == "max":
                    out[t] = max(nums)
                elif agg == "min":
                    out[t] = min(nums)
                elif agg == "avg":
                    out[t] = sum(nums) / len(nums)
                else:                     # quantile over a scalar kind:
                    out[t] = max(nums)    # degrade to max, never crash
        return out

    def _boundaries_for(self, kind: str, counts: List[int]
                        ) -> Tuple[float, ...]:
        # All series of one histogram name share boundaries (enforced at
        # _apply_row); grab them from any live histogram with this bucket
        # count. Caller holds no lock on _series here by design: this is
        # only reached from query() which already holds self._lock... so
        # read directly.
        for s in self._series["h"].values():
            if s.boundaries is not None and \
                    len(s.boundaries) + 1 == len(counts):
                return s.boundaries
        return tuple(float(i) for i in range(len(counts) - 1))

    def latest(self, name: str, tags: Optional[Dict[str, str]] = None,
               agg: str = "sum", now: Optional[float] = None
               ) -> Optional[float]:
        """Most recent aggregated value (short lookback window)."""
        res = self.query(name, tags=tags, agg=agg,
                         since_s=self.fine_step * 3, now=now)
        return res["points"][-1][1] if res["points"] else None

    def series(self, prefix: Optional[str] = None) -> List[Dict]:
        with self._lock:
            out = []
            for kind, table in self._series.items():
                for s in table.values():
                    if prefix and not s.name.startswith(prefix):
                        continue
                    out.append({"name": s.name, "kind": kind,
                                "tags": dict(s.tags)})
        return sorted(out, key=lambda d: (d["name"], sorted(d["tags"].items())))

    def prometheus_text(self) -> str:
        """Cluster-aggregated exposition: every shipped series with its
        ``proc`` label, cumulative totals (what Prometheus expects)."""
        lines: List[str] = []
        seen_header: set = set()
        with self._lock:
            allseries = [s for table in self._series.values()
                         for s in table.values()]
        for s in sorted(allseries, key=lambda x: (x.name, x.tags)):
            if s.name not in seen_header:
                seen_header.add(s.name)
                ptype = {"c": "counter", "g": "gauge",
                         "h": "histogram"}[s.kind]
                lines.append(f"# TYPE {s.name} {ptype}")
            lbl = _labels(s.tags)
            if s.kind == "c":
                lines.append(f"{s.name}{lbl} {_fmt(s.total)}")
            elif s.kind == "g":
                lines.append(f"{s.name}{lbl} {_fmt(s.last)}")
            else:
                cum = 0
                for b, c in zip(s.boundaries, s.bucket_totals):
                    cum += c
                    lines.append(
                        f"{s.name}_bucket{_labels(s.tags, le=_fmt(b))} {cum}")
                cum += s.bucket_totals[-1]
                lines.append(
                    f"{s.name}_bucket{_labels(s.tags, le='+Inf')} {cum}")
                lines.append(f"{s.name}_sum{lbl} {_fmt(s.sum_total)}")
                lines.append(f"{s.name}_count{lbl} {s.count_total}")
        return "\n".join(lines) + ("\n" if lines else "")

    def stats(self) -> Dict:
        with self._lock:
            return {
                "series": sum(len(t) for t in self._series.values()),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "series_evicted": self.series_evicted,
                "frames_applied": self.frames_applied,
                "frames_deduped": self.frames_deduped,
                "frames_rejected": self.frames_rejected,
                "rows_dropped": self.rows_dropped,
                "upstream_drops": self.upstream_drops,
                "dead_procs": len(self._dead_procs),
            }

    def note_upstream_drops(self, n: int) -> None:
        """Shippers count frames their bounded buffers had to drop; the
        head folds those counts here so truncation is visible, not
        silent (same contract as TaskEventStore's dropped counter)."""
        if n > 0:
            with self._lock:
                self.upstream_drops += int(n)


def _labels(tags: Tuple, **extra: str) -> str:
    items = list(tags) + sorted(extra.items())
    if not items:
        return ""
    body = ",".join(f'{k}="{str(v)}"' for k, v in items)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _bucket_quantile(counts: List[int], boundaries: Tuple[float, ...],
                     q: float) -> Optional[float]:
    """Prometheus-style ``histogram_quantile``: linear interpolation
    inside the target bucket; the overflow bucket clamps to the highest
    boundary (same convention the reference uses for +Inf)."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        if cum + c >= target:
            if i >= len(boundaries):          # +Inf bucket
                return float(boundaries[-1]) if boundaries else None
            lo = float(boundaries[i - 1]) if i > 0 else 0.0
            hi = float(boundaries[i])
            return lo + (hi - lo) * ((target - cum) / c)
        cum += c
    return float(boundaries[-1]) if boundaries else None


# ---------------------------------------------------------------------------
# SLO alerts: threshold/duration rules over queried series, evaluated on
# the head's health-loop cadence and fired into the ops-event log.
# ---------------------------------------------------------------------------

_RULE_RE = re.compile(
    r"^\s*([A-Za-z_]\w*)\s*(?:\{([^}]*)\})?\s*(?::\s*(\w+))?\s*([<>]=?)\s*"
    r"([-+0-9.eE]+)\s*(?:for\s+([0-9.]+)\s*s?)?\s*$")


def _parse_selector(body: str) -> Dict[str, str]:
    """Parse the ``{k=v,...}`` tag selector of an alert-rule spec
    (e.g. ``raytpu_tenant_queued{tenant=acme} > 100 for 30s``).
    Values may be bare tokens or quoted; an empty body means no
    tag filter."""
    tags: Dict[str, str] = {}
    for pair in body.split(","):
        pair = pair.strip()
        if not pair:
            continue
        if "=" not in pair:
            raise ValueError(f"bad tag selector {pair!r}")
        k, v = pair.split("=", 1)
        k, v = k.strip(), v.strip().strip("'\"")
        if not k or not v:
            raise ValueError(f"bad tag selector {pair!r}")
        tags[k] = v
    return tags


class AlertRule:
    """One threshold/duration rule, e.g. parsed from
    ``raytpu_infer_ttft_seconds:p95 > 2.0 for 30s``."""

    def __init__(self, metric: str, op: str, threshold: float,
                 agg: str = "max", for_s: float = 0.0,
                 tags: Optional[Dict[str, str]] = None):
        if agg not in AGGS:
            raise ValueError(f"unknown agg {agg!r}")
        if op not in (">", "<", ">=", "<="):
            raise ValueError(f"unknown op {op!r}")
        self.metric = metric
        self.agg = agg
        self.op = op
        self.threshold = float(threshold)
        self.for_s = float(for_s)
        self.tags = dict(tags or {})

    @property
    def name(self) -> str:
        sel = ""
        if self.tags:
            sel = "{" + ",".join(
                f"{k}={self.tags[k]}" for k in sorted(self.tags)) + "}"
        return (f"{self.metric}{sel}:{self.agg} {self.op} "
                f"{_fmt(self.threshold)} for {_fmt(self.for_s)}s")

    def breached(self, value: float) -> bool:
        return {">": value > self.threshold,
                "<": value < self.threshold,
                ">=": value >= self.threshold,
                "<=": value <= self.threshold}[self.op]


def parse_alert_rules(spec: str) -> List[AlertRule]:
    """Parse a ``;``-separated rule list (the ``metrics_alert_rules``
    config knob). Malformed entries raise — a silently-dropped SLO rule
    is worse than a loud startup failure."""
    rules: List[AlertRule] = []
    for part in (spec or "").split(";"):
        if not part.strip():
            continue
        m = _RULE_RE.match(part)
        if not m:
            raise ValueError(f"bad alert rule: {part!r}")
        metric, sel, agg, op, thr, for_s = m.groups()
        rules.append(AlertRule(metric, op, float(thr), agg=agg or "max",
                               for_s=float(for_s) if for_s else 0.0,
                               tags=_parse_selector(sel) if sel else None))
    return rules


def serve_slo_preset_rules(spec: str, for_s: float = 30.0) -> List[AlertRule]:
    """Expand a per-tenant TTFT SLO preset (``tenant=seconds;...``) into
    alert rules. ``"acme=0.5; free-tier=2"`` becomes two p95 rules over
    ``raytpu_serve_ttft_seconds``, each scoped to its tenant's tag so a
    breach fires on the breaching tenant only. Tenant names may contain
    characters the generic rule grammar rejects (hyphens, dots), which
    is why this builds ``AlertRule`` objects directly instead of
    round-tripping through ``parse_alert_rules``. Malformed entries
    raise — same loud-startup policy as the generic parser."""
    rules: List[AlertRule] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad SLO preset (want tenant=seconds): {part!r}")
        tenant, thr = part.split("=", 1)
        tenant, thr = tenant.strip(), thr.strip()
        if not tenant or not thr:
            raise ValueError(f"bad SLO preset (want tenant=seconds): {part!r}")
        try:
            threshold = float(thr)
        except ValueError:
            raise ValueError(
                f"bad SLO preset threshold (want seconds): {part!r}")
        rules.append(AlertRule(
            "raytpu_serve_ttft_seconds", ">", threshold,
            agg="p95", for_s=for_s, tags={"tenant": tenant}))
    return rules


class AlertEvaluator:
    """Tick on the head's health-loop cadence; a rule fires once when
    its breach has been sustained ``for_s`` seconds and resolves when
    the breach clears (hysteresis lives in the duration, not here)."""

    def __init__(self, store: MetricStore, rules: List[AlertRule],
                 on_fire: Callable[[AlertRule, float], None],
                 on_resolve: Optional[Callable[[AlertRule, float], None]]
                 = None):
        self.store = store
        self.rules = list(rules)
        self.on_fire = on_fire
        self.on_resolve = on_resolve
        self._state: Dict[str, Dict] = {}

    def set_rules(self, rules: List[AlertRule]) -> None:
        self.rules = list(rules)
        live = {r.name for r in rules}
        for k in [k for k in self._state if k not in live]:
            del self._state[k]

    def firing(self) -> List[str]:
        return sorted(k for k, st in self._state.items() if st["firing"])

    def tick(self, now: Optional[float] = None) -> None:
        if now is None:
            now = self.store._clock()
        for rule in self.rules:
            try:
                value = self.store.latest(rule.metric, tags=rule.tags,
                                          agg=rule.agg, now=now)
            except ValueError:
                continue
            st = self._state.setdefault(
                rule.name, {"since": None, "firing": False})
            breach = value is not None and rule.breached(value)
            if breach:
                if st["since"] is None:
                    st["since"] = now
                if not st["firing"] and now - st["since"] >= rule.for_s:
                    st["firing"] = True
                    self.on_fire(rule, value)
            else:
                if st["firing"] and self.on_resolve is not None:
                    self.on_resolve(rule, value if value is not None else 0.0)
                st["since"] = None
                st["firing"] = False
