"""Serve SLO instruments: per-request latency series + the goodput ledger.

Reference analogue: Ray Serve's per-deployment latency/QPS metrics and
the goodput accounting argued for in disaggregated-serving work
(DistServe: SLO *attainment* — tokens that reached a consumer inside
their latency budget — is the capacity metric, not raw throughput).

One module owns every serving-plane SLO instrument so the router
(driver process), replica (worker process), engine scheduler and the
client-side response generator all book into the SAME named series:

- ``raytpu_serve_ttft_seconds`` / ``raytpu_serve_tpot_seconds`` /
  ``raytpu_serve_e2e_seconds`` / ``raytpu_serve_queue_seconds`` —
  per-deployment+tenant histograms, observed ONCE per request (TPOT is
  the mean inter-token gap ``(t_last - t_first) / (n - 1)``, not a
  per-token observation — the hot loop never touches a histogram).
- ``raytpu_serve_tokens_delivered_total`` vs
  ``raytpu_serve_tokens_wasted_total{cause}`` — the goodput ledger.
  ``delivered - wasted`` over ``delivered`` is the goodput ratio shown
  in ``raytpu top``. Causes: ``abort`` (consumer vanished / stream
  failed: tokens decoded or received but never used),
  ``preempt_recompute`` (generated tokens whose KV a preemption
  discarded — they will be re-prefilled), ``handoff_fallback`` (prompt
  tokens a failed KV pull forces back through local prefill).

All instruments ride the ordinary delta-shipping metrics pipeline, so
they are inert (local dict bumps, nothing shipped) unless
``RAYTPU_METRICS`` is armed; the tenant tag uses the reserved
cardinality headroom so SLO evidence never folds into ``<other>``.
"""

from __future__ import annotations

from raytpu.util.metrics import Counter, Histogram

_LAT_BOUNDARIES = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0, 30.0)
_TPOT_BOUNDARIES = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                    0.25, 0.5, 1.0)

DEFAULT_TENANT = "default"

ttft_hist = Histogram(
    "raytpu_serve_ttft_seconds",
    "Request time-to-first-token, by deployment and tenant",
    boundaries=_LAT_BOUNDARIES, tag_keys=("deployment", "tenant"))
tpot_hist = Histogram(
    "raytpu_serve_tpot_seconds",
    "Inter-token latency (time per output token), by deployment/tenant",
    boundaries=_TPOT_BOUNDARIES, tag_keys=("deployment", "tenant"))
e2e_hist = Histogram(
    "raytpu_serve_e2e_seconds",
    "Request end-to-end latency, by deployment and tenant",
    boundaries=_LAT_BOUNDARIES, tag_keys=("deployment", "tenant"))
queue_hist = Histogram(
    "raytpu_serve_queue_seconds",
    "Replica queue wait (enqueue to semaphore), by deployment/tenant",
    boundaries=_LAT_BOUNDARIES, tag_keys=("deployment", "tenant"))
tokens_delivered = Counter(
    "raytpu_serve_tokens_delivered_total",
    "Tokens streamed to consumers, by deployment and tenant",
    tag_keys=("deployment", "tenant"))
tokens_wasted = Counter(
    "raytpu_serve_tokens_wasted_total",
    "Tokens whose work was discarded, by cause",
    tag_keys=("cause", "deployment", "tenant"))


def _tags(deployment: str, tenant: str) -> dict:
    return {"deployment": deployment or "", "tenant": tenant or
            DEFAULT_TENANT}


def observe_ttft(seconds: float, deployment: str, tenant: str) -> None:
    ttft_hist.observe(seconds, _tags(deployment, tenant))


def observe_tpot(seconds: float, deployment: str, tenant: str) -> None:
    tpot_hist.observe(seconds, _tags(deployment, tenant))


def observe_e2e(seconds: float, deployment: str, tenant: str) -> None:
    e2e_hist.observe(seconds, _tags(deployment, tenant))


def observe_queue(seconds: float, deployment: str, tenant: str) -> None:
    queue_hist.observe(seconds, _tags(deployment, tenant))


def delivered(n: int, deployment: str, tenant: str) -> None:
    if n > 0:
        tokens_delivered.inc(n, _tags(deployment, tenant))


def wasted(cause: str, n: int, deployment: str = "",
           tenant: str = "") -> None:
    if n > 0:
        tokens_wasted.inc(n, {"cause": cause, **_tags(deployment, tenant)})
