"""ActorPool (reference: ``python/ray/util/actor_pool.py``)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending = []  # (fn, value) waiting for an idle actor
        self._result_queue = []

    def submit(self, fn: Callable, value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
        else:
            self._pending.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending)

    def get_next(self, timeout=None) -> Any:
        import raytpu

        if not self.has_next():
            raise StopIteration("no pending results")
        refs = list(self._future_to_actor.keys())
        ready, _ = raytpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        ref = ready[0]
        actor = self._future_to_actor.pop(ref)
        self._return_actor(actor)
        return raytpu.get(ref)

    get_next_unordered = get_next

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    map_unordered = map

    def _return_actor(self, actor):
        if self._pending:
            fn, value = self._pending.pop(0)
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
        else:
            self._idle.append(actor)

    def has_free(self) -> bool:
        return bool(self._idle)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        self._return_actor(actor)
