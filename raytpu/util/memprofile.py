"""Allocation memory profiler (tracemalloc-based).

Reference analogue: ``dashboard/modules/reporter/profile_manager.py``
(``memray attach`` memory profiles of any live worker). memray isn't
shippable in a zero-egress image, so the equivalent capability uses the
stdlib: ``tracemalloc`` traces every Python allocation with a bounded
traceback depth; a profile window starts tracing (if not already on),
waits, snapshots, and aggregates live allocations into collapsed stacks
keyed by allocation traceback — the same ``root;child;leaf size``
format the CPU profiler emits, so the one flamegraph renderer serves
both (frames weighted by KiB instead of samples).

What tracemalloc cannot see (and memray can): native allocations that
never cross the Python allocator (e.g. jaxlib/XLA buffers). The
process-level RSS reported alongside covers the gap at coarse grain.
"""

from __future__ import annotations

import os
import sys
import time
import tracemalloc
from typing import Dict, List, Optional


def _rss_kb() -> Optional[int]:
    """Resident set size in KiB from /proc (Linux; None elsewhere)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") // 1024)
    except Exception:  # noqa: BLE001 — non-Linux
        return None


_MAX_STACKS = 2000  # collapsed entries per profile; tail folds to <other>


def memory_profile(duration_s: float = 2.0, trace_frames: int = 16,
                   top_n: int = 40, stop_after: bool = False) -> dict:
    """Profile this process's live Python allocations.

    Starts ``tracemalloc`` if it isn't tracing (so the first call's
    window only sees allocations made DURING the window — stated in the
    result as ``window_only``), waits ``duration_s`` for the workload to
    allocate, then snapshots. Returns::

        {"collapsed": {stack: KiB}, "total_kb": ..., "peak_kb": ...,
         "rss_kb": ..., "top": [{"stack": [...], "kb": N, "count": M}],
         "window_only": bool, "pid": ..., "duration_s": ...}

    ``collapsed`` stacks are ``alloc;outer (file:line);...;leaf`` with
    KiB weights (sub-KiB sites aggregate in bytes first, so thousands
    of tiny allocations can't dwarf one real buffer), capped at the
    ``_MAX_STACKS`` largest sites with the tail folded into
    ``alloc;<other>`` — a long-lived worker may hold 100k+ distinct
    tracebacks and this dict travels over RPC. Feed to
    ``profiler.flamegraph_svg`` directly. ``stop_after=True`` turns
    tracing off afterwards (removes the ~2-4x allocation overhead,
    loses the baseline for the next call).
    """
    duration_s = max(0.0, min(float(duration_s), 120.0))
    trace_frames = max(1, min(int(trace_frames), 64))
    window_only = not tracemalloc.is_tracing()
    if window_only:
        tracemalloc.start(trace_frames)
    try:
        if duration_s:
            time.sleep(duration_s)
        snap = tracemalloc.take_snapshot()
        current, peak = tracemalloc.get_traced_memory()
        # start() is a no-op while tracing: report the depth actually
        # in effect, not the requested one.
        effective_frames = tracemalloc.get_traceback_limit()
    finally:
        if stop_after:
            tracemalloc.stop()
    stats = snap.statistics("traceback")
    by_bytes: Dict[str, int] = {}
    top: List[dict] = []
    for st in stats:  # statistics() is sorted by size, largest first
        frames = [f"{os.path.basename(fr.filename)}:{fr.lineno}"
                  for fr in st.traceback]  # oldest (root) first
        key = ";".join(["alloc"] + frames)
        if key in by_bytes or len(by_bytes) < _MAX_STACKS:
            by_bytes[key] = by_bytes.get(key, 0) + st.size
        else:
            by_bytes["alloc;<other>"] = \
                by_bytes.get("alloc;<other>", 0) + st.size
        if len(top) < top_n:
            top.append({"stack": frames, "kb": st.size // 1024,
                        "count": st.count})
    # Sub-KiB sites must not round up to 1 KiB each (2000 tiny sites
    # would overstate the flamegraph by ~2 MiB): fold them into the
    # <other> bucket in BYTES, then convert once.
    other_bytes = by_bytes.pop("alloc;<other>", 0)
    collapsed: Dict[str, int] = {}
    for k, b in by_bytes.items():
        kb = b // 1024
        if kb == 0:
            other_bytes += b
        else:
            collapsed[k] = kb
    if other_bytes:
        collapsed["alloc;<other>"] = max(1, other_bytes // 1024)
    return {"collapsed": collapsed,
            "total_kb": current // 1024,
            "peak_kb": peak // 1024,
            "rss_kb": _rss_kb(),
            "top": top,
            "window_only": window_only,
            "pid": os.getpid(),
            "duration_s": duration_s,
            "trace_frames": effective_frames}


def top_table(profile: dict, limit: int = 25) -> str:
    """Human-readable top-allocations table (memray's summary view)."""
    lines = [f"pid {profile.get('pid', '?')}: "
             f"python-live {profile.get('total_kb', 0):,} KiB, "
             f"peak {profile.get('peak_kb', 0):,} KiB, "
             f"rss {profile.get('rss_kb') or 0:,} KiB"
             + ("  [window-only trace]" if profile.get("window_only")
                else "")]
    for row in sorted(profile.get("top", []),
                      key=lambda r: -r["kb"])[:limit]:
        leaf = row["stack"][-1] if row["stack"] else "?"
        lines.append(f"{row['kb']:>10,} KiB  {row['count']:>7}x  {leaf}")
    return "\n".join(lines)
