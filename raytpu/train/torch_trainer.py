"""TorchTrainer — migration-compat trainer for existing torch loops.

Reference analogue: ``python/ray/train/torch/torch_trainer.py`` +
``torch/train_loop_utils.py`` (``prepare_model``/``prepare_data_loader``).
The compute plane here is JAX by design (MIGRATION.md), but reference
users arrive with working ``train_loop_per_worker`` functions written
against torch — this trainer runs them unchanged: the same gang/PG/
rendezvous/report/checkpoint machinery as :class:`JaxTrainer`, with the
process group formed by ``torch.distributed`` (gloo — this image has no
CUDA/NCCL; the point is API-compatible CPU execution and a mechanical
migration path to ``JaxTrainer``).
"""

from __future__ import annotations

from raytpu.train.trainer import JaxTrainer


class TorchTrainer(JaxTrainer):
    distributed_backend = "torch"


def prepare_model(model):
    """DDP-wrap when a multi-worker process group exists (reference:
    ``ray.train.torch.prepare_model`` — device move + DDP; CPU/gloo
    here, so only the DDP wrap applies)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Shard a DataLoader across the gang with a DistributedSampler
    (reference: ``ray.train.torch.prepare_data_loader``). The incoming
    loader's shuffle intent is preserved (an eval loader built with
    ``shuffle=False`` stays ordered). Pass-through cases: world size 1,
    non-map-style datasets, and ``batch_sampler`` loaders (their
    ``batch_size`` is None — rebuilding would disable batching).

    For shuffling loaders, call ``loader.sampler.set_epoch(epoch)`` at
    each epoch start (standard DistributedSampler contract) or every
    epoch reuses one permutation."""
    import torch.distributed as dist

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    from torch.utils.data import (DataLoader, DistributedSampler,
                                  RandomSampler)

    ds = data_loader.dataset
    if not hasattr(ds, "__len__"):
        return data_loader
    if data_loader.batch_size is None:
        return data_loader  # batch_sampler loader: see docstring
    shuffle = isinstance(data_loader.sampler, RandomSampler)
    sampler = DistributedSampler(ds, num_replicas=dist.get_world_size(),
                                 rank=dist.get_rank(), shuffle=shuffle)
    return DataLoader(ds, batch_size=data_loader.batch_size,
                      sampler=sampler,
                      num_workers=getattr(data_loader, "num_workers", 0),
                      collate_fn=data_loader.collate_fn,
                      drop_last=data_loader.drop_last)
