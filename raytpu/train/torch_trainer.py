"""TorchTrainer — migration-compat trainer for existing torch loops.

Reference analogue: ``python/ray/train/torch/torch_trainer.py`` +
``torch/train_loop_utils.py`` (``prepare_model``/``prepare_data_loader``).
The compute plane here is JAX by design (MIGRATION.md), but reference
users arrive with working ``train_loop_per_worker`` functions written
against torch — this trainer runs them unchanged: the same gang/PG/
rendezvous/report/checkpoint machinery as :class:`JaxTrainer`, with the
process group formed by ``torch.distributed`` (gloo — this image has no
CUDA/NCCL; the point is API-compatible CPU execution and a mechanical
migration path to ``JaxTrainer``).
"""

from __future__ import annotations

from raytpu.train.trainer import JaxTrainer


class TorchTrainer(JaxTrainer):
    distributed_backend = "torch"


def prepare_model(model):
    """DDP-wrap when a multi-worker process group exists (reference:
    ``ray.train.torch.prepare_model`` — device move + DDP; CPU/gloo
    here, so only the DDP wrap applies)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Shard a DataLoader across the gang with a DistributedSampler
    (reference: ``ray.train.torch.prepare_data_loader``). The incoming
    loader's shuffle intent is preserved (an eval loader built with
    ``shuffle=False`` stays ordered). Pass-through cases: world size 1,
    non-map-style datasets, and ``batch_sampler`` loaders (their
    ``batch_size`` is None — rebuilding would disable batching).

    For shuffling loaders, call ``loader.sampler.set_epoch(epoch)`` at
    each epoch start (standard DistributedSampler contract) or every
    epoch reuses one permutation."""
    import torch.distributed as dist

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return data_loader
    from torch.utils.data import (DataLoader, DistributedSampler,
                                  RandomSampler)

    ds = data_loader.dataset
    if not hasattr(ds, "__len__"):
        _warn_unsharded("an iterable-style dataset (no __len__)")
        return data_loader
    if data_loader.batch_size is None:
        _warn_unsharded("a batch_sampler loader (batch_size is None)")
        return data_loader
    from torch.utils.data import SequentialSampler

    old_sampler = data_loader.sampler
    shuffle = isinstance(old_sampler, RandomSampler)
    if not shuffle and not isinstance(old_sampler, SequentialSampler):
        import warnings

        warnings.warn(
            f"prepare_data_loader: replacing custom sampler "
            f"{type(old_sampler).__name__} with an unshuffled "
            f"DistributedSampler — its sampling semantics (weighting, "
            f"ordering) are LOST. Apply the custom logic inside the "
            f"dataset, or shard manually by rank.", UserWarning,
            stacklevel=2)
    sampler = DistributedSampler(ds, num_replicas=dist.get_world_size(),
                                 rank=dist.get_rank(), shuffle=shuffle)
    num_workers = getattr(data_loader, "num_workers", 0)
    kwargs = dict(
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=num_workers,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
        pin_memory=getattr(data_loader, "pin_memory", False),
        worker_init_fn=getattr(data_loader, "worker_init_fn", None),
        generator=getattr(data_loader, "generator", None),
        timeout=getattr(data_loader, "timeout", 0),
    )
    if num_workers > 0:
        # Only legal to pass with worker processes (DataLoader raises
        # on prefetch_factor/persistent_workers at num_workers=0).
        kwargs["persistent_workers"] = getattr(
            data_loader, "persistent_workers", False)
        pf = getattr(data_loader, "prefetch_factor", None)
        if pf is not None:
            kwargs["prefetch_factor"] = pf
        kwargs["multiprocessing_context"] = getattr(
            data_loader, "multiprocessing_context", None)
    return DataLoader(ds, **kwargs)


def _warn_unsharded(why: str) -> None:
    import warnings

    import torch.distributed as dist

    warnings.warn(
        f"prepare_data_loader: cannot shard {why} at world size "
        f"{dist.get_world_size()} — EVERY worker will iterate the FULL "
        f"dataset (duplicate epochs). Shard inside the dataset itself "
        f"(e.g. by rank) or switch to a map-style dataset with a "
        f"batch_size loader.", UserWarning, stacklevel=3)
