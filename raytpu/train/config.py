"""Train/Tune shared configs.

Reference analogue: ``python/ray/air/config.py`` — ``ScalingConfig``
(``:103``), ``FailureConfig`` (``:395``), ``CheckpointConfig`` (``:445``),
``RunConfig`` (``:594``). TPU-first deltas: workers are sized in *chips*
(``chips_per_worker``) and ScalingConfig emits STRICT_PACK placement-group
bundles so each worker's chips form a contiguous ICI sub-box
(reference translation: ``as_placement_group_factory``,
``air/config.py:268-278`` — see SURVEY.md A6).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class ScalingConfig:
    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "STRICT_PACK"  # chips must be ICI-contiguous
    # Multi-host gang rendezvous: when set, every worker runs
    # jax.distributed.initialize(coordinator_address, num_workers, rank)
    # (reference analogue: the TCP-store rendezvous of
    # _setup_torch_process_group, torch/config.py:65). Cluster mode fills
    # this from the head's address; leave None for single-host.
    coordinator_address: Optional[str] = None
    # Gang-elastic training (reference analogue: torchelastic's
    # min/max nnodes): on gang failure with ``elastic=True`` the trainer
    # may re-form the gang at any world size in
    # ``[min_workers, num_workers]`` instead of insisting on full
    # strength, resuming from the latest checkpoint (resharded via pjit
    # on restore), and scales back up to ``num_workers`` at a checkpoint
    # boundary once capacity returns. ``min_workers=None`` means the
    # gang is fixed-size even when ``elastic`` is set.
    min_workers: Optional[int] = None
    elastic: bool = False

    def bundle_specs(self, world_size: Optional[int] = None
                     ) -> List[Dict[str, float]]:
        """One bundle per worker (reference: A6 — the zero-CPU trainer
        bundle is merged into rank 0). ``world_size`` overrides
        ``num_workers`` for elastic gangs running below full strength."""
        per = dict(self.resources_per_worker or {})
        per.setdefault("CPU", 1)
        if self.use_tpu and self.chips_per_worker:
            per.setdefault("TPU", self.chips_per_worker)
        n = self.num_workers if world_size is None else world_size
        return [dict(per) for _ in range(n)]

    @property
    def total_chips(self) -> int:
        return self.num_workers * self.chips_per_worker if self.use_tpu else 0


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0  # retries of the whole worker group (gang restart)


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    verbose: int = 0


@dataclasses.dataclass
class Result:
    metrics: Dict[str, Any]
    metrics_history: List[Dict[str, Any]]
    checkpoint: Optional[Any]
    path: Optional[str]
    error: Optional[BaseException] = None
    # The trial's hyperparameter config (reference: ``Result.config``).
    config: Optional[Dict[str, Any]] = None
