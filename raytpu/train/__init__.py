"""raytpu.train — distributed training orchestration (reference:
``python/ray/train/``)."""

from raytpu.train.checkpoint import (
    Checkpoint,
    CheckpointManager,
    restore_pytree,
    save_pytree,
)
from raytpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from raytpu.train.session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from raytpu.train.torch_trainer import (TorchTrainer, prepare_data_loader,
                                        prepare_model)
from raytpu.train.trainer import (BaseTrainer,
                                  DataParallelTrainer,
                                  JaxTrainer)
from raytpu.util.stepprof import StepProfiler, cost_analysis_flops

__all__ = [
    "BaseTrainer",
    "JaxTrainer",
    "DataParallelTrainer",
    "TorchTrainer",
    "prepare_model",
    "prepare_data_loader",
    "ScalingConfig",
    "RunConfig",
    "FailureConfig",
    "CheckpointConfig",
    "Result",
    "Checkpoint",
    "CheckpointManager",
    "save_pytree",
    "restore_pytree",
    "report",
    "get_context",
    "get_checkpoint",
    "get_dataset_shard",
    "StepProfiler",
    "cost_analysis_flops",
]

from raytpu.util import usage_stats as _usage_stats

_usage_stats.record_library_usage("train")
