"""Per-worker training session.

Reference analogue: ``python/ray/train/_internal/session.py`` —
``_TrainSession`` (``:109``), ``report`` (``:661,401``). The user loop
calls :func:`report` each step/epoch; metrics and an optional checkpoint
flow back to the trainer, which persists checkpoints and feeds Tune.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from raytpu.util.profiler import profiling_enabled
from raytpu.util.stepprof import step_profiler


@dataclass
class TrainContext:
    rank: int = 0
    world_size: int = 1
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    storage_path: Optional[str] = None
    chip_coords: Optional[list] = None

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_local_rank(self) -> int:
        return self.local_rank


class _Session:
    def __init__(self, context: TrainContext, dataset_shards=None):
        self.context = context
        # Each entry is (metrics, checkpoint-or-None): pairing is preserved
        # so every checkpoint is registered with ITS metrics, and none are
        # lost between polls.
        self.reports: List[tuple] = []
        self.latest_checkpoint = None  # resume-from slot (read at startup)
        self.lock = threading.Lock()
        # Long-poll support: signaled on every report so the trainer's
        # poll blocks instead of spinning (a 50ms poll loop measurably
        # taxed the train loop itself on small hosts).
        self.news = threading.Condition(self.lock)
        self.closed = False  # loop finished/failed: pollers must not block
        self.dataset_shards = dataset_shards or {}

    def report(self, metrics: Dict[str, Any], checkpoint=None):
        # Step attribution: consecutive report() calls bound one step.
        # MFU needs the loop to pass its per-step FLOPs (key "flops" or
        # "step_flops", e.g. from stepprof.cost_analysis_flops); without
        # it only the step-time histogram moves.
        if profiling_enabled():
            prof = step_profiler("train")
            dt = prof.mark()
            if dt is not None:
                f = metrics.get("flops") or metrics.get("step_flops")
                prof.observe_step(dt, flops=float(f) if f else None)
                prof.observe_hbm()
        # Only rank 0's checkpoint is persisted by the trainer (single-
        # controller design) — dropping the others here avoids staging a
        # full copy per worker per report that nobody ever drains.
        if checkpoint is not None and self.context.rank != 0:
            checkpoint = None
        # Snapshot the checkpoint dir SYNCHRONOUSLY before returning:
        # the reference's report() blocks until the checkpoint is
        # persisted, which is what makes the canonical
        # ``with TemporaryDirectory() as d: report(..., Checkpoint(d))``
        # idiom safe. Draining happens later, possibly after `d` is gone.
        if checkpoint is not None and getattr(checkpoint, "path", None):
            import shutil
            import tempfile
            import uuid

            base = self.context.storage_path or tempfile.gettempdir()
            staged = os.path.join(base, ".staged_ckpts", uuid.uuid4().hex)
            os.makedirs(os.path.dirname(staged), exist_ok=True)
            shutil.copytree(checkpoint.path, staged)
            checkpoint = type(checkpoint)(staged)
        with self.lock:
            self.reports.append((dict(metrics), checkpoint))
            self.news.notify_all()

    def wake(self) -> None:
        """The loop finished or failed: mark closed and release pollers.
        The flag is read inside the condition's predicate, so a finish
        landing between a poller's done-check and its wait cannot strand
        the poll for the full timeout (lost-wakeup race)."""
        with self.lock:
            self.closed = True
            self.news.notify_all()

    def drain(self) -> List[tuple]:
        with self.lock:
            out = self.reports
            self.reports = []
            return out

    def wait_for_news(self, timeout: float) -> None:
        """Block until a report lands or the loop closes (or timeout)."""
        with self.lock:
            self.news.wait_for(
                lambda: bool(self.reports) or self.closed, timeout)


_tls = threading.local()


def _set_session(s: Optional[_Session]):
    _tls.session = s


def _get_session() -> Optional[_Session]:
    return getattr(_tls, "session", None)


def report(metrics: Dict[str, Any], checkpoint=None) -> None:
    """Report metrics (+ optional checkpoint) from inside the training loop
    (reference: ``train.report``)."""
    s = _get_session()
    if s is None:
        raise RuntimeError("report() called outside a training session")
    s.report(metrics, checkpoint)


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        return TrainContext()
    return s.context


def get_checkpoint():
    """Checkpoint to resume from, if the trainer restored one."""
    s = _get_session()
    return s.latest_checkpoint if s else None


def get_dataset_shard(name: str = "train"):
    """This worker's streaming data shard (reference:
    ``session.get_dataset_shard`` backed by ``streaming_split``,
    ``python/ray/data/dataset.py:1141``)."""
    s = _get_session()
    if s is None or name not in s.dataset_shards:
        raise KeyError(
            f"no dataset shard {name!r}; pass datasets={{'{name}': ds}} to "
            "the trainer")
    return s.dataset_shards[name]
