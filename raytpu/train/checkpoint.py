"""Checkpoints: directory handles + orbax-backed sharded-array IO.

Reference analogue: ``python/ray/train/_checkpoint.py:56`` (Checkpoint as
a directory handle), ``_internal/checkpoint_manager.py`` (top-K retention),
``_internal/storage.py:505`` (persist). TPU delta (SURVEY.md §5
checkpoint/resume): the payload is a *sharded* jax pytree saved with
orbax — every host writes only its shards, restore re-shards to the
current mesh.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional, Tuple


class Checkpoint:
    """A handle to a checkpoint directory."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="raytpu-ckpt-")
        import cloudpickle

        with open(os.path.join(d, "data.pkl"), "wb") as f:
            cloudpickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        import cloudpickle

        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def as_directory(self) -> str:
        return self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"


def save_pytree(tree, path: str) -> Checkpoint:
    """Save a (possibly sharded) jax pytree with orbax."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, tree)
    return Checkpoint(path)


def restore_pytree(path_or_ckpt, target=None, shardings=None):
    """Restore a pytree; with `shardings` the arrays materialize directly
    into the current mesh layout (no host round-trip on multi-chip)."""
    import orbax.checkpoint as ocp

    path = (path_or_ckpt.path if isinstance(path_or_ckpt, Checkpoint)
            else os.path.abspath(path_or_ckpt))
    ckptr = ocp.PyTreeCheckpointer()
    if shardings is not None:
        import jax

        restore_args = jax.tree_util.tree_map(
            lambda s: ocp.ArrayRestoreArgs(sharding=s), shardings)
        return ckptr.restore(path, restore_args=restore_args)
    if target is not None:
        return ckptr.restore(path, item=target)
    return ckptr.restore(path)


class CheckpointManager:
    """Top-K checkpoint retention (reference:
    ``_internal/checkpoint_manager.py``)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._entries: List[Tuple[float, int, str]] = []  # (score, idx, path)
        # Continue numbering past any pre-existing checkpoint_NNNNNN dirs
        # (restored experiments): restarting at 0 would overwrite dirs a
        # saved experiment state still references.
        self._counter = 0
        try:
            for name in os.listdir(self.root):
                if name.startswith("checkpoint_"):
                    try:
                        self._counter = max(self._counter,
                                            int(name.split("_")[1]))
                    except (IndexError, ValueError):
                        pass
        except OSError:
            pass

    def register(self, checkpoint: Checkpoint,
                 metrics: Dict[str, Any]) -> Checkpoint:
        """Move the checkpoint into managed storage; evict beyond top-K."""
        self._counter += 1
        dst = os.path.join(self.root, f"checkpoint_{self._counter:06d}")
        src = os.path.abspath(checkpoint.path)
        if src != dst:
            if os.path.exists(dst):
                shutil.rmtree(dst)
            if f"{os.sep}.staged_ckpts{os.sep}" in src:
                # Session-staged snapshot: single-owner, safe to move
                # (avoids a second copy and cleans the staging area).
                shutil.move(src, dst)
            else:
                shutil.copytree(src, dst)
        with open(os.path.join(dst, "_metrics.json"), "w") as f:
            json.dump(_jsonable(metrics), f)
        score = self._score(metrics)
        self._entries.append((score, self._counter, dst))
        self._evict()
        return Checkpoint(dst)

    def best(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        reverse = self.score_order == "max"
        entries = sorted(self._entries, key=lambda e: e[0], reverse=reverse)
        return Checkpoint(entries[0][2])

    def latest(self) -> Optional[Checkpoint]:
        if not self._entries:
            return None
        return Checkpoint(max(self._entries, key=lambda e: e[1])[2])

    def _score(self, metrics: Dict[str, Any]) -> float:
        if self.score_attribute and self.score_attribute in metrics:
            return float(metrics[self.score_attribute])
        # Recency fallback, sign-adjusted so "more recent ranks better"
        # holds under BOTH score orders.
        return float(self._counter if self.score_order == "max"
                     else -self._counter)

    def _evict(self):
        if self.num_to_keep is None or len(self._entries) <= self.num_to_keep:
            return
        reverse = self.score_order == "max"
        ranked = sorted(self._entries, key=lambda e: e[0], reverse=reverse)
        keep = set(id(e) for e in ranked[: self.num_to_keep])
        # Always keep the latest for resume.
        latest = max(self._entries, key=lambda e: e[1])
        keep.add(id(latest))
        survivors = []
        for e in self._entries:
            if id(e) in keep:
                survivors.append(e)
            else:
                shutil.rmtree(e[2], ignore_errors=True)
        self._entries = survivors


def _jsonable(d: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except (TypeError, ValueError):
            out[k] = str(v)
    return out
