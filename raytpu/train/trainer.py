"""JaxTrainer — the distributed-training orchestrator.

Reference analogue (SURVEY.md §3.4 call stack): ``BaseTrainer.fit``
(``python/ray/train/base_trainer.py:567``) → ``DataParallelTrainer``
(``data_parallel_trainer.py:22``) → ``BackendExecutor`` (PG creation at
``_internal/backend_executor.py:197``) → ``WorkerGroup``
(``_internal/worker_group.py:102``) → per-worker ``_TrainSession``.

TPU-first redesign: the worker group is a *gang* — one worker actor per
host, each owning a contiguous-ICI bundle of chips; rendezvous runs
``jax.distributed.initialize`` with the coordinator published through the
control plane (reference pattern: NCCLUniqueIDStore named actor, SURVEY.md
A5); the training loop itself is single-program SPMD over the global mesh,
so there is no gradient-bucket machinery to orchestrate — XLA owns the
collectives. Elastic recovery is gang-shaped too (FailureConfig →
checkpoint + gang restart, not per-task retry).
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Any, Callable, Dict, Optional

import raytpu
from raytpu.cluster import constants as tuning
from raytpu.train import session as session_mod
from raytpu.train.checkpoint import Checkpoint, CheckpointManager
from raytpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from raytpu.util import errors


@raytpu.remote(num_cpus=0)
class _RendezvousStore:
    """Named actor publishing the gang coordinator address through the
    control plane — the analogue of the reference's NCCLUniqueIDStore
    named actor (SURVEY.md A5; ``util/collective/.../NCCLUniqueIDStore``).
    Keyed by gang attempt so a restarted gang never reads a dead
    incarnation's address."""

    def __init__(self):
        self._addrs: Dict[int, str] = {}

    def set_addr(self, attempt: int, addr: str) -> bool:
        self._addrs[attempt] = addr
        return True

    def get_addr(self, attempt: int) -> Optional[str]:
        return self._addrs.get(attempt)


@raytpu.remote(num_cpus=0)
class TrainWorker:
    """One gang member: hosts the user loop in a thread + a session."""

    def __init__(self, rank: int, world_size: int, context_kwargs: dict):
        self.rank = rank
        self.world_size = world_size
        self.context = session_mod.TrainContext(
            rank=rank, world_size=world_size, local_rank=rank,
            **context_kwargs)
        self.session = None
        self.thread = None
        self.error = None
        self.done = False

    def setup_distributed(self, coordinator: Optional[str],
                          num_processes: int, process_id: int,
                          rdzv_name: Optional[str] = None,
                          attempt: int = 0, backend: str = "jax"):
        """Multi-host rendezvous (reference analogue:
        ``_setup_torch_process_group``, ``torch/config.py:65``).

        ``coordinator="auto"``: rank 0 binds a free port on its host and
        publishes ``host:port`` through the :class:`_RendezvousStore`
        named actor; other ranks poll it. Then every rank runs
        ``jax.distributed.initialize`` so the gang forms one global JAX
        runtime (the mesh spans all hosts' devices).
        """
        if coordinator is None or num_processes <= 1:
            if backend == "torch":
                if num_processes > 1:
                    # JAX in-process workers share one runtime, so a None
                    # coordinator is fine there — torch has no shared
                    # runtime: an uninitialized process group would train
                    # N diverging replicas with zero gradient sync.
                    raise ValueError(
                        "TorchTrainer with num_workers > 1 requires "
                        "ScalingConfig(coordinator_address='auto' or "
                        "'host:port') to form the gloo process group")
                self._init_torch_pg("127.0.0.1:0", 1, 0)
            return True
        if coordinator == "auto":
            store = raytpu.get_actor(rdzv_name)
            if process_id == 0:
                import socket

                host = os.environ.get("RAYTPU_HOST_IP", "127.0.0.1")
                s = socket.socket()
                s.bind((host, 0))
                port = s.getsockname()[1]
                s.close()
                coordinator = f"{host}:{port}"
                raytpu.get(store.set_addr.remote(attempt, coordinator))
            else:
                deadline = time.monotonic() + 60.0
                while True:
                    coordinator = raytpu.get(
                        store.get_addr.remote(attempt))
                    if coordinator:
                        break
                    if time.monotonic() >= deadline:
                        raise TimeoutError(
                            "rendezvous: coordinator address never "
                            "published")
                    time.sleep(0.1)
        if backend == "torch":
            self._init_torch_pg(coordinator, num_processes, process_id)
            return True
        import jax

        # Honor the spawn-time platform choice: plugin sitecustomize hooks
        # (e.g. accelerator tunnels) may have overridden jax_platforms at
        # interpreter startup, and backend init would then block on an
        # unavailable accelerator instead of using what the node intended.
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            try:
                jax.config.update("jax_platforms", plat)
            except Exception as e:
                errors.swallow("train.gang_teardown", e)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True

    @staticmethod
    def _init_torch_pg(coordinator: str, num_processes: int,
                       process_id: int) -> None:
        """Migration-compat gang (reference: _setup_torch_process_group,
        torch/config.py:65): gloo over the same rendezvous plumbing. The
        timeout bounds EVERY collective for the life of training, so it
        defaults to the reference's 1800s (``torch_pg_timeout_s``), not
        a rendezvous-scale value."""
        import datetime

        import torch.distributed as dist

        from raytpu.core.config import cfg

        if dist.is_initialized():
            return
        if coordinator.endswith(":0"):  # world-size-1 local group
            import socket

            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            coordinator = f"127.0.0.1:{s.getsockname()[1]}"
            s.close()
        dist.init_process_group(
            "gloo", init_method=f"tcp://{coordinator}",
            rank=process_id, world_size=num_processes,
            timeout=datetime.timedelta(
                seconds=float(cfg.torch_pg_timeout_s)))

    def start(self, train_fn_blob: bytes, config: dict, dataset_shards=None,
              resume_path=None):
        import threading

        import cloudpickle

        train_fn = cloudpickle.loads(train_fn_blob)
        self.session = session_mod._Session(self.context, dataset_shards)
        if resume_path:
            self.session.latest_checkpoint = Checkpoint(resume_path)

        def run():
            session_mod._set_session(self.session)
            try:
                train_fn(config)
            except BaseException as e:  # noqa: BLE001
                self.error = e
            finally:
                self.done = True
                session_mod._set_session(None)
                self.session.wake()  # unblock any in-flight long-poll

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()
        return True

    def poll(self, max_wait: float = 0.0):
        """Returns ([(metrics, ckpt_path_or_None), ...], done, error_repr).

        ``max_wait > 0`` long-polls: blocks until a report lands, the
        loop finishes, or the timeout passes — the trainer drives this
        at ~0.5s instead of a tight 50ms spin (which measurably stole
        cycles from the train loop on small hosts and multiplied RPCs
        on clusters).

        `done` is read BEFORE draining: if the loop finishes between the
        drain and the flag read, the final reports are still picked up on
        the trainer's next (guaranteed, because done was False) poll."""
        if max_wait > 0 and self.session and not self.done \
                and self.error is None:
            self.session.wait_for_news(max_wait)
        done = self.done
        pairs = self.session.drain() if self.session else []
        out = [(m, (c.path if c is not None else None)) for m, c in pairs]
        err = None
        if self.error is not None:
            import traceback

            err = "".join(traceback.format_exception(
                type(self.error), self.error, self.error.__traceback__))
        return out, done, err



class BaseTrainer:
    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError


class JaxTrainer(BaseTrainer):
    """Data-parallel (and beyond — the mesh decides) JAX trainer.

    train_loop_per_worker(config) runs on every gang member; inside it use
    ``raytpu.train.report`` / ``get_context`` / ``get_dataset_shard`` and
    the mesh helpers in :mod:`raytpu.parallel`.
    """

    # Which process-group flavor setup_distributed forms for the gang.
    distributed_backend = "jax"

    def __init__(self, train_loop_per_worker: Callable[[dict], None], *,
                 train_loop_config: Optional[dict] = None,
                 datasets: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.datasets = datasets or {}

    def fit(self) -> Result:
        import cloudpickle

        sc = self.scaling_config
        rc = self.run_config
        name = rc.name or f"raytpu-train-{int(time.time())}"
        storage = rc.storage_path or os.path.join(
            tempfile.gettempdir(), "raytpu_results")
        run_dir = os.path.join(storage, name)
        os.makedirs(run_dir, exist_ok=True)
        manager = CheckpointManager(
            os.path.join(run_dir, "checkpoints"),
            num_to_keep=rc.checkpoint_config.num_to_keep,
            score_attribute=rc.checkpoint_config.checkpoint_score_attribute,
            score_order=rc.checkpoint_config.checkpoint_score_order,
        )

        rdzv = None
        rdzv_name = None
        if sc.coordinator_address == "auto" and sc.num_workers > 1:
            rdzv_name = f"rdzv::{name}"
            # Restartable: the store must survive node loss — losing it
            # would burn every gang-retry attempt on rendezvous failures.
            # A restarted (empty) incarnation is fine: each attempt
            # publishes under its own key.
            rdzv = _RendezvousStore.options(
                name=rdzv_name, max_restarts=100).remote()

        attempts = rc.failure_config.max_failures + 1
        elastic = bool(sc.elastic and sc.min_workers
                       and sc.min_workers < sc.num_workers)
        floor = max(1, min(sc.min_workers or sc.num_workers,
                           sc.num_workers))
        fn_blob = cloudpickle.dumps(self.train_loop_per_worker)
        last_error = None
        failures = 0
        world = sc.num_workers
        history: list = []
        try:
            incarnation = 0  # rendezvous key: unique per gang formed
            while True:
                result = self._run_gang(sc, name, run_dir, manager,
                                        fn_blob, rdzv_name=rdzv_name,
                                        attempt=incarnation,
                                        world_size=world,
                                        target_world=(sc.num_workers
                                                      if elastic else None))
                incarnation += 1
                # Continuous history across gang incarnations: a resumed
                # run is ONE experiment, not N.
                history.extend(result.metrics_history)
                if isinstance(result.error, _GangRescale):
                    # Capacity returned mid-run; the gang parked at a
                    # checkpoint boundary. Re-form at full strength —
                    # this is progress, not a failure: no budget burned.
                    world = result.error.world
                    self.resume_from_checkpoint = manager.latest()
                    continue
                if result.error is None:
                    return Result(
                        metrics=history[-1] if history else {},
                        metrics_history=history,
                        checkpoint=result.checkpoint,
                        path=run_dir, error=None)
                last_error = result.error
                failures += 1
                if failures >= attempts:
                    break
                # Gang restart from the latest checkpoint (SURVEY.md §7
                # hard part (d): elastic recovery = checkpoint + gang
                # restart).
                self.resume_from_checkpoint = manager.latest()
                if elastic:
                    # Probe live capacity: the biggest feasible world
                    # size in [floor, num_workers]. Training resumes
                    # degraded rather than burning the whole failure
                    # budget waiting for a full-strength cluster.
                    world = _probe_world_size(sc, floor,
                                              sc.num_workers) or world
            return Result(metrics=history[-1] if history else {},
                          metrics_history=history, checkpoint=None,
                          path=run_dir, error=last_error)
        finally:
            if rdzv is not None:
                try:
                    raytpu.kill(rdzv)
                except Exception as e:
                    errors.swallow("train.gang_teardown", e)
            # Staged snapshots that were never registered (failed gangs,
            # undrained reports) are garbage once fit() returns.
            import shutil

            shutil.rmtree(os.path.join(run_dir, ".staged_ckpts"),
                          ignore_errors=True)

    # -- internals ------------------------------------------------------------

    def _run_gang(self, sc: ScalingConfig, name: str, run_dir: str,
                  manager: CheckpointManager, fn_blob: bytes,
                  rdzv_name: Optional[str] = None,
                  attempt: int = 0,
                  world_size: Optional[int] = None,
                  target_world: Optional[int] = None) -> Result:
        from raytpu.core.errors import TaskError

        n = world_size or sc.num_workers
        pg = None
        workers = []
        history = []
        last_ckpt = None
        # Scale-back-up bookkeeping (elastic gang below full strength):
        # capacity is probed at most once per check period, and only a
        # checkpoint boundary may trigger the rescale — re-forming the
        # gang anywhere else would lose progress since the last save.
        next_upscale_check = time.monotonic() \
            + tuning.ELASTIC_UPSCALE_CHECK_PERIOD_S
        try:
            bundles = sc.bundle_specs(n)
            pg = raytpu.placement_group(bundles,
                                        strategy=sc.placement_strategy)
            shards = _split_datasets(self.datasets, n)
            for rank in range(n):
                ctx_kwargs = {
                    "experiment_name": name,
                    "storage_path": run_dir,
                    "chip_coords": pg.chip_coords(rank) if sc.use_tpu else None,
                }
                w = TrainWorker.options(
                    placement_group=pg,
                    placement_group_bundle_index=rank,
                ).remote(rank, n, ctx_kwargs)
                workers.append(w)
            # Gang rendezvous: jax.distributed.initialize runs only when a
            # coordinator address is configured (multi-host cluster mode);
            # in-process workers share one JAX runtime and must skip it.
            raytpu.get([
                w.setup_distributed.remote(
                    sc.coordinator_address, n, i,
                    rdzv_name, attempt, self.distributed_backend)
                for i, w in enumerate(workers)])
            resume = (self.resume_from_checkpoint.path
                      if self.resume_from_checkpoint is not None else None)
            raytpu.get([
                w.start.remote(fn_blob, self.train_loop_config,
                               shards[i], resume)
                for i, w in enumerate(workers)])

            error = None
            while True:
                # Long-poll rank 0 (it drives metrics/checkpoints); other
                # ranks answer instantly. No driver-side spin: the worker
                # wakes us on report/finish (see TrainWorker.poll).
                polls = raytpu.get(
                    [w.poll.remote(0.5 if i == 0 else 0.0)
                     for i, w in enumerate(workers)])
                ckpt_this_round = False
                for metrics, ckpt_path in polls[0][0]:  # rank 0 drives
                    history.append(metrics)
                    if ckpt_path:
                        last_ckpt = manager.register(
                            Checkpoint(ckpt_path), metrics)
                        ckpt_this_round = True
                if ckpt_this_round and target_world and n < target_world \
                        and time.monotonic() >= next_upscale_check:
                    # Checkpoint boundary while degraded: if replacement
                    # capacity can hold the FULL gang's extra bundles,
                    # park here and let fit() re-form at full strength,
                    # resuming from the checkpoint just registered.
                    next_upscale_check = time.monotonic() \
                        + tuning.ELASTIC_UPSCALE_CHECK_PERIOD_S
                    if _world_feasible(sc, target_world, held=n):
                        return Result(
                            metrics=history[-1] if history else {},
                            metrics_history=history,
                            checkpoint=last_ckpt or manager.latest(),
                            path=run_dir,
                            error=_GangRescale(target_world),
                        )
                errs = [p[2] for p in polls if p[2]]
                if errs:
                    error = TaskError("train_loop_per_worker", errs[0])
                    break
                if all(p[1] for p in polls):
                    break
                # Pace every round: a loop reporting hundreds of times a
                # second must not drive a poll round per report — drains
                # batch. Idle gangs park in the long-poll either way.
                time.sleep(0.05)
            return Result(
                metrics=history[-1] if history else {},
                metrics_history=history,
                checkpoint=last_ckpt or manager.latest(),
                path=run_dir,
                error=error,
            )
        except Exception as e:
            # Gang-shaped failure: a member (or its node/PG) died. Surface
            # it as a failed Result so fit()'s FailureConfig loop restarts
            # the whole gang from the latest checkpoint (SURVEY §7 hard
            # part (d)) instead of crashing the driver.
            return Result(
                metrics=history[-1] if history else {},
                metrics_history=history,
                checkpoint=last_ckpt or manager.latest(),
                path=run_dir,
                error=e if isinstance(e, TaskError) else TaskError(
                    "train_gang", f"gang failure: {type(e).__name__}: {e}"),
            )
        finally:
            for w in workers:
                try:
                    raytpu.kill(w)
                except Exception as e:
                    errors.swallow("train.gang_teardown", e)
            if pg is not None:
                try:
                    raytpu.remove_placement_group(pg)
                except Exception as e:
                    errors.swallow("train.gang_teardown", e)


class _GangRescale(Exception):
    """Internal fit() control flow, never user-visible: an elastic gang
    running below full strength found capacity for ``world`` workers and
    parked at a checkpoint boundary so fit() can re-form it bigger."""

    def __init__(self, world: int):
        super().__init__(f"rescale gang to {world} workers")
        self.world = world


def _world_feasible(sc: ScalingConfig, world: int, held: int = 0) -> bool:
    """Can a ``world``-worker gang place on the live cluster right now?

    Greedy first-fit of ``sc.bundle_specs(world)`` onto each alive
    node's available resources — the driver-side mirror of the head's
    PG packer, cheap enough to poll. ``held``: bundles the CURRENT gang
    already occupies (released the moment fit() re-forms it), so an
    upscale probe only needs ``world - held`` fresh bundles. For
    STRICT_PACK the held bundles are known to sit on one node, and the
    probe requires a single node covering the full need net of them —
    slightly optimistic when another node matches, in which case the
    rescale attempt fails PG creation and the elastic loop recovers.
    """
    bundles = sc.bundle_specs(world)
    if not bundles:
        return True
    try:
        infos = raytpu.nodes()
    except Exception as e:
        errors.swallow("train.elastic_probe", e)
        return False
    # The cluster client returns reference-style capitalized keys
    # ("Alive"/"Available"/"Labels"); the local backend lowercase ones.
    avail = []
    for i in infos:
        labels = i.get("Labels") or i.get("labels") or {}
        if not i.get("Alive", i.get("alive")) \
                or labels.get("role") == "driver":
            continue
        avail.append(dict(i.get("Available") or i.get("available") or {}))
    if sc.placement_strategy == "STRICT_PACK":
        need: Dict[str, float] = {}
        for b in bundles[held:]:
            for k, v in b.items():
                need[k] = need.get(k, 0.0) + v
        return any(all(a.get(k, 0.0) >= v - 1e-9
                       for k, v in need.items()) for a in avail)
    for b in bundles[held:]:
        for a in avail:
            if all(a.get(k, 0.0) >= v - 1e-9 for k, v in b.items()):
                for k, v in b.items():
                    a[k] = a.get(k, 0.0) - v
                break
        else:
            return False
    return True


def _probe_world_size(sc: ScalingConfig, floor: int,
                      ceiling: int) -> Optional[int]:
    """Post-failure capacity probe: wait up to ELASTIC_PROBE_TIMEOUT_S
    for ANY feasible world size in ``[floor, ceiling]``, preferring the
    biggest. Returns None when nothing fits within the budget — the
    caller retries at its previous size and lets the gang failure
    surface normally."""
    deadline = time.monotonic() + tuning.ELASTIC_PROBE_TIMEOUT_S
    while True:
        for world in range(ceiling, floor - 1, -1):
            if _world_feasible(sc, world):
                return world
        if time.monotonic() >= deadline:
            return None
        time.sleep(tuning.ELASTIC_PROBE_PERIOD_S)


def _split_datasets(datasets: Dict[str, Any], n: int):
    """Per-worker dataset shards via streaming_split (reference:
    ``DataConfig.configure_ingest``, SURVEY.md A8)."""
    shards = [dict() for _ in range(n)]
    for key, ds in datasets.items():
        if hasattr(ds, "streaming_split"):
            its = ds.streaming_split(n)
            for i in range(n):
                shards[i][key] = its[i]
        else:
            for i in range(n):
                shards[i][key] = ds
    return shards


# Reference-parity alias: the reference's trainer hierarchy roots at
# DataParallelTrainer (python/ray/train/data_parallel_trainer.py);
# JaxTrainer IS our data-parallel trainer.
DataParallelTrainer = JaxTrainer
