"""raytpu.job — job submission (reference: dashboard/modules/job/)."""

from raytpu.job.manager import JobInfo, JobManager
from raytpu.job.sdk import JobSubmissionClient
from raytpu.job.server import JobServer

__all__ = ["JobInfo", "JobManager", "JobServer", "JobSubmissionClient"]
