"""Job REST API server.

Reference analogue: ``dashboard/modules/job/job_head.py`` — the REST
surface (`/api/jobs/`) the SDK and CLI talk to. aiohttp server running in
its own thread over a :class:`JobManager`.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from raytpu.job.manager import JobManager


class JobServer:
    def __init__(self, manager: JobManager, host: str = "127.0.0.1",
                 port: int = 0):
        self.manager = manager
        self._host = host
        self._port = port
        self._started = threading.Event()
        self._stopping: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.address: Optional[str] = None

    def start(self) -> str:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="raytpu-job-server")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("job server failed to start")
        return self.address

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._serve())

    async def _serve(self) -> None:
        from aiohttp import web

        self._stopping = asyncio.Event()
        app = web.Application()
        app.router.add_post("/api/jobs/", self._submit)
        app.router.add_get("/api/jobs/", self._list)
        app.router.add_get("/api/jobs/{job_id}", self._get)
        app.router.add_get("/api/jobs/{job_id}/logs", self._logs)
        app.router.add_post("/api/jobs/{job_id}/stop", self._stop)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self._host, self._port)
        await site.start()
        self._port = site._server.sockets[0].getsockname()[1]
        self.address = f"http://{self._host}:{self._port}"
        self._started.set()
        await self._stopping.wait()
        await runner.cleanup()

    def stop(self) -> None:
        if self._loop is not None and self._stopping is not None:
            try:
                self._loop.call_soon_threadsafe(self._stopping.set)
            except RuntimeError:
                pass

    # -- handlers ----------------------------------------------------------

    async def _submit(self, request):
        from aiohttp import web

        body = await request.json()
        try:
            job_id = self.manager.submit_job(
                body["entrypoint"],
                submission_id=body.get("submission_id"),
                runtime_env=body.get("runtime_env"),
                metadata=body.get("metadata"),
            )
        except (KeyError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response({"job_id": job_id,
                                  "submission_id": job_id})

    async def _list(self, request):
        from aiohttp import web

        return web.json_response(
            [j.to_dict() for j in self.manager.list_jobs()])

    async def _get(self, request):
        from aiohttp import web

        try:
            info = self.manager.get_job_info(
                request.match_info["job_id"])
        except KeyError:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response(info.to_dict())

    async def _logs(self, request):
        from aiohttp import web

        try:
            logs = self.manager.get_job_logs(request.match_info["job_id"])
        except KeyError:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response({"logs": logs})

    async def _stop(self, request):
        from aiohttp import web

        try:
            stopped = self.manager.stop_job(request.match_info["job_id"])
        except KeyError:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response({"stopped": stopped})
