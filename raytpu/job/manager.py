"""Job manager: run driver scripts as supervised subprocesses.

Reference analogue: ``dashboard/modules/job/job_manager.py`` — each
submitted job gets a supervisor that launches the entrypoint shell command
with the cluster address in its environment, captures logs, tracks a
status state machine (PENDING → RUNNING → SUCCEEDED/FAILED/STOPPED), and
supports stop. The reference supervises via an actor; ours supervises with
a thread per job (the job itself is always a separate process).
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = "PENDING"  # PENDING|RUNNING|SUCCEEDED|FAILED|STOPPED
    submission_time: float = field(default_factory=time.time)
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    return_code: Optional[int] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    log_path: str = ""
    message: str = ""

    def to_dict(self) -> dict:
        return dict(self.__dict__)


class JobManager:
    def __init__(self, cluster_address: Optional[str] = None,
                 log_dir: Optional[str] = None):
        self.cluster_address = cluster_address
        self.log_dir = log_dir or os.path.join(
            os.path.expanduser("~/.raytpu"), "job_logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def submit_job(self, entrypoint: str, *,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
            info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                           metadata=dict(metadata or {}),
                           log_path=os.path.join(self.log_dir,
                                                 f"{job_id}.log"))
            self._jobs[job_id] = info
        threading.Thread(target=self._supervise,
                         args=(info, dict(runtime_env or {})),
                         name=f"job-{job_id}", daemon=True).start()
        return job_id

    def _supervise(self, info: JobInfo, runtime_env: dict) -> None:
        env = dict(os.environ)
        env.update({str(k): str(v)
                    for k, v in (runtime_env.get("env_vars") or {}).items()})
        if self.cluster_address:
            env["RAYTPU_ADDRESS"] = self.cluster_address
        cwd = runtime_env.get("working_dir") or os.getcwd()
        try:
            log_f = open(info.log_path, "wb")
        except OSError as e:
            info.status = "FAILED"
            info.message = f"cannot open log file: {e}"
            return
        try:
            proc = subprocess.Popen(
                info.entrypoint, shell=True, cwd=cwd, env=env,
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True,  # own group so stop kills children
            )
        except OSError as e:
            info.status = "FAILED"
            info.message = str(e)
            log_f.close()
            return
        with self._lock:
            self._procs[info.job_id] = proc
            # stop_job may already have marked it STOPPED between launch
            # and here; RUNNING must not clobber that.
            if info.status == "PENDING":
                info.status = "RUNNING"
        info.start_time = time.time()
        rc = proc.wait()
        log_f.close()
        info.end_time = time.time()
        info.return_code = rc
        with self._lock:
            if info.status != "STOPPED":
                info.status = "SUCCEEDED" if rc == 0 else "FAILED"
                if rc != 0:
                    info.message = f"entrypoint exited with code {rc}"
        with self._lock:
            self._procs.pop(info.job_id, None)

    def stop_job(self, job_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(job_id)
            proc = self._procs.get(job_id)
        if info is None:
            raise KeyError(job_id)
        if proc is None or proc.poll() is not None:
            return False
        with self._lock:
            info.status = "STOPPED"
        info.message = "stopped by user"
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except ProcessLookupError:
            pass
        # escalate after a grace period
        def _escalate():
            time.sleep(3)
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
        threading.Thread(target=_escalate, daemon=True).start()
        return True

    def get_job_info(self, job_id: str) -> JobInfo:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            raise KeyError(job_id)
        return info

    def get_job_status(self, job_id: str) -> str:
        return self.get_job_info(job_id).status

    def get_job_logs(self, job_id: str) -> str:
        info = self.get_job_info(job_id)
        try:
            with open(info.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def list_jobs(self) -> List[JobInfo]:
        with self._lock:
            return list(self._jobs.values())

    def wait_until_finished(self, job_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            time.sleep(0.1)
        raise TimeoutError(f"job {job_id} still "
                           f"{self.get_job_status(job_id)}")
