"""Job submission SDK.

Reference analogue: ``dashboard/modules/job/sdk.py:39``
(``JobSubmissionClient``) — the typed HTTP client for the job REST API.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import requests


class JobSubmissionClient:
    def __init__(self, address: str):
        self.address = address.rstrip("/")

    def _url(self, path: str) -> str:
        return f"{self.address}{path}"

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        r = requests.post(self._url("/api/jobs/"), json={
            "entrypoint": entrypoint,
            "submission_id": submission_id,
            "runtime_env": runtime_env,
            "metadata": metadata,
        }, timeout=30)
        if r.status_code != 200:
            raise RuntimeError(f"submit failed: {r.status_code} {r.text}")
        return r.json()["job_id"]

    def get_job_status(self, job_id: str) -> str:
        return self.get_job_info(job_id)["status"]

    def get_job_info(self, job_id: str) -> dict:
        r = requests.get(self._url(f"/api/jobs/{job_id}"), timeout=30)
        if r.status_code == 404:
            raise KeyError(job_id)
        return r.json()

    def get_job_logs(self, job_id: str) -> str:
        r = requests.get(self._url(f"/api/jobs/{job_id}/logs"), timeout=30)
        if r.status_code == 404:
            raise KeyError(job_id)
        return r.json()["logs"]

    def stop_job(self, job_id: str) -> bool:
        r = requests.post(self._url(f"/api/jobs/{job_id}/stop"),
                          timeout=30)
        if r.status_code == 404:
            raise KeyError(job_id)
        return r.json()["stopped"]

    def list_jobs(self) -> List[dict]:
        r = requests.get(self._url("/api/jobs/"), timeout=30)
        return r.json()

    def wait_until_finished(self, job_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} not finished in {timeout}s")
