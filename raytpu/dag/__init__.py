from raytpu.dag.node import DAGNode, FunctionNode, ActorMethodNode, ClassNode, InputNode
from raytpu.dag.compiled import CompiledDAG, CompiledDAGRef, MultiOutputNode

__all__ = [
    "ActorMethodNode", "ClassNode", "CompiledDAG", "CompiledDAGRef",
    "DAGNode", "FunctionNode", "InputNode", "MultiOutputNode",
]
