from raytpu.dag.node import DAGNode, FunctionNode, ActorMethodNode, ClassNode, InputNode

__all__ = ["DAGNode", "FunctionNode", "ActorMethodNode", "ClassNode", "InputNode"]
