"""Compiled DAGs: pre-allocated channel pipelines across actors.

Reference analogue: ``python/ray/dag/compiled_dag_node.py`` —
``CompiledDAG`` (``:174``) and the per-actor exec loop
(``do_exec_compiled_task``, ``:90-110``): compile once, then every
``execute()`` writes the input into a channel and each actor runs
read-inputs → invoke-method → write-output with NO per-step task
submission. This is the microsecond-pipeline path; on TPU it is how
multi-actor pipelines (e.g. host data prep → trainer step → metrics sink)
avoid submission overhead between steps.

Supported topology: one ``InputNode``, any DAG of ``ActorMethodNode``s over
``ClassNode``/``ActorHandle`` targets, optionally a ``MultiOutputNode``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from raytpu.dag.node import (
    ActorMethodNode,
    ClassNode,
    DAGNode,
    InputNode,
)
from raytpu.runtime.channel import Channel, ChannelClosed


class MultiOutputNode(DAGNode):
    """Bundle several leaf nodes into one execute() result tuple."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})
        self.outputs = list(outputs)

    def execute(self, input_value: Any = None):
        return [o.execute(input_value) for o in self.outputs]


class _Teardown:
    """Sentinel flushed through the pipeline to stop exec loops."""

    def __reduce__(self):
        return (_teardown_singleton, ())


_TEARDOWN = _Teardown()


def _teardown_singleton():
    return _TEARDOWN


class _ExecError:
    """An exception captured in some upstream node, propagated downstream
    so the driver re-raises it from get() (reference: compiled DAGs forward
    errors through channels the same way)."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def _exec_compiled_loop(self_callable, method_name: str,
                        in_channels: List[Channel],
                        in_reader_ids: List[int],
                        const_args: tuple, const_kwargs: dict,
                        arg_slots: List,
                        out_channel: Channel) -> str:
    """Parked inside the actor as one long-running task. ``arg_slots[i]``
    says where in_channels[i]'s value goes: an int is a positional slot,
    a str a keyword name; ``const_args``/``const_kwargs`` fill the rest
    (None placeholders at channel slots)."""
    method = getattr(self_callable, method_name)
    while True:
        vals = []
        err: Optional[_ExecError] = None
        stop = False
        for ch, rid in zip(in_channels, in_reader_ids):
            try:
                v = ch.read(rid)
            except ChannelClosed:
                stop = True
                break
            if isinstance(v, _Teardown):
                stop = True
                break
            if isinstance(v, _ExecError) and err is None:
                err = v
            vals.append(v)
        if stop:
            try:
                out_channel.write(_TEARDOWN)
            except ChannelClosed:
                pass
            return "stopped"
        if err is not None:
            out_channel.write(err)
            continue
        args = list(const_args)
        kwargs = dict(const_kwargs)
        for slot, v in zip(arg_slots, vals):
            if isinstance(slot, str):
                kwargs[slot] = v
            else:
                args[slot] = v
        try:
            result = method(*args, **kwargs)
        except BaseException as e:  # propagate, keep looping
            result = _ExecError(e)
        try:
            out_channel.write(result)
        except ChannelClosed:
            return "stopped"


class CompiledDAGRef:
    """Future for one execute(); reads the output channel in order."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._value: Any = None
        self._done = False

    def get(self, timeout: Optional[float] = None) -> Any:
        if not self._done:
            self._dag._drain_until(self._seq, timeout)
            self._value = self._dag._results.pop(self._seq)
            self._done = True
        value = self._value
        if isinstance(value, _ExecError):
            raise value.exc
        if isinstance(value, list):
            for v in value:
                if isinstance(v, _ExecError):
                    raise v.exc
        return value


class CompiledDAG:
    def __init__(self, root: DAGNode, buffer_size: int = 16):
        self._root = root
        self._buffer_size = buffer_size
        self._input_channel: Optional[Channel] = None
        self._output_channels: List[Channel] = []
        self._output_reader_ids: List[int] = []
        self._loop_refs: list = []
        # _meta_lock guards counters/flags only (never held while blocking
        # on a channel); _drain_lock serializes output readers, so a parked
        # get() can't deadlock execute()/teardown().
        self._meta_lock = threading.Lock()
        self._drain_lock = threading.Lock()
        self._exec_lock = threading.Lock()  # keeps seq == input-write order
        self._seq = 0            # next execute() sequence number
        self._read_seq = 0       # next sequence to read from outputs
        self._partial_outs: List[Any] = []  # mid-tuple reads after timeout
        self._results: Dict[int, Any] = {}
        self._multi_output = isinstance(root, MultiOutputNode)
        self._torn_down = False
        self._compile()

    # -- compilation -------------------------------------------------------

    def _compile(self) -> None:
        leaves = self._root.outputs if self._multi_output else [self._root]
        # node -> its output channel; count consumers first.
        consumers: Dict[int, int] = {}
        nodes: List[ActorMethodNode] = []
        seen: Dict[int, ActorMethodNode] = {}
        input_consumers = 0

        def walk(node: DAGNode):
            nonlocal input_consumers
            if not isinstance(node, ActorMethodNode):
                raise TypeError(
                    "compiled DAGs support actor-method nodes (got "
                    f"{type(node).__name__}); tasks have no persistent "
                    "process to park the exec loop in"
                )
            if id(node) in seen:
                return
            seen[id(node)] = node
            for a in list(node._bound_args) + list(node._bound_kwargs.values()):
                if isinstance(a, InputNode):
                    input_consumers += 1
                elif isinstance(a, ActorMethodNode):
                    consumers[id(a)] = consumers.get(id(a), 0) + 1
                    walk(a)
                elif isinstance(a, DAGNode):
                    raise TypeError(
                        f"unsupported node type in compiled DAG: "
                        f"{type(a).__name__}"
                    )
            nodes.append(node)

        for leaf in leaves:
            walk(leaf)
            consumers[id(leaf)] = consumers.get(id(leaf), 0) + 1  # driver

        self._input_channel = Channel(
            num_readers=max(1, input_consumers),
            capacity=self._buffer_size)
        channels: Dict[int, Channel] = {
            nid: Channel(num_readers=n, capacity=self._buffer_size)
            for nid, n in consumers.items()
        }

        # Launch one exec loop per node (topological order from walk()).
        for node in nodes:
            target = node._target
            if isinstance(target, ClassNode):
                handle = target.execute()
            else:
                handle = target
            in_channels, in_rids, slots = [], [], []
            const_args: List[Any] = []
            const_kwargs: Dict[str, Any] = {}

            def wire(a, slot):
                if isinstance(a, InputNode):
                    in_channels.append(self._input_channel)
                    in_rids.append(self._input_channel.reader_id())
                    slots.append(slot)
                    return None, True
                if isinstance(a, ActorMethodNode):
                    ch = channels[id(a)]
                    in_channels.append(ch)
                    in_rids.append(ch.reader_id())
                    slots.append(slot)
                    return None, True
                return a, False

            for i, a in enumerate(node._bound_args):
                v, _ = wire(a, i)
                const_args.append(v)
            for k, a in node._bound_kwargs.items():
                v, wired = wire(a, k)
                if not wired:
                    const_kwargs[k] = v
            ref = _submit_loop(handle, node, in_channels, in_rids,
                               tuple(const_args), const_kwargs,
                               slots, channels[id(node)])
            self._loop_refs.append(ref)

        for leaf in leaves:
            ch = channels[id(leaf)]
            self._output_channels.append(ch)
            self._output_reader_ids.append(ch.reader_id())

    # -- execution ---------------------------------------------------------

    def execute(self, input_value: Any = None,
                timeout: Optional[float] = None) -> CompiledDAGRef:
        with self._exec_lock:
            with self._meta_lock:
                if self._torn_down:
                    raise RuntimeError("compiled DAG was torn down")
                seq = self._seq
                self._seq += 1
            # Channel capacity provides backpressure; a parked get() holds
            # only _drain_lock, so it can never block this write.
            self._input_channel.write(input_value, timeout=timeout)
        return CompiledDAGRef(self, seq)

    def _drain_until(self, seq: int, timeout: Optional[float]) -> None:
        with self._drain_lock:
            while self._read_seq <= seq:
                # _partial_outs survives a timeout mid-tuple so a retried
                # get() resumes at the unread channel instead of re-reading
                # channel 0 (which would misalign MultiOutputNode results
                # across sequence numbers).
                outs = self._partial_outs
                while len(outs) < len(self._output_channels):
                    i = len(outs)
                    outs.append(self._output_channels[i].read(
                        self._output_reader_ids[i], timeout=timeout))
                self._partial_outs = []
                with self._meta_lock:
                    self._results[self._read_seq] = (
                        list(outs) if self._multi_output else outs[0]
                    )
                    self._read_seq += 1

    def teardown(self) -> None:
        with self._meta_lock:
            if self._torn_down:
                return
            self._torn_down = True
        try:
            self._input_channel.write(_TEARDOWN, timeout=5.0)
        except Exception:
            self._input_channel.close()
        import raytpu

        for ref in self._loop_refs:
            try:
                raytpu.get(ref, timeout=5.0)
            except Exception:
                pass
        for ch in [self._input_channel] + self._output_channels:
            ch.close()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


def _submit_loop(handle, node, in_channels, in_rids, const_args,
                 const_kwargs, slots, out_channel):
    """Park _exec_compiled_loop inside the actor. Every actor dispatches
    the reserved ``__raytpu_exec_compiled__`` method name to the loop
    (runtime/worker.py execute path)."""
    from raytpu.runtime.actor import ActorMethod

    return ActorMethod(handle, "__raytpu_exec_compiled__", 1).remote(
        node._method_name, in_channels, in_rids, const_args, const_kwargs,
        slots, out_channel)


def experimental_compile(dag: DAGNode, buffer_size: int = 16) -> CompiledDAG:
    return CompiledDAG(dag, buffer_size=buffer_size)
