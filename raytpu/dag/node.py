"""Lazy DAG of ``.bind()`` calls.

Reference analogue: ``python/ray/dag/dag_node.py`` (DAGNode) and classic
execution via ``.execute()``. Compiled execution (pre-allocated channels,
reference ``compiled_dag_node.py:174``) is mostly subsumed on TPU by
compiled XLA programs; the host-side channel pipeline lives in
:mod:`raytpu.dag.compiled`.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple


class DAGNode:
    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _resolve(self, value, input_value):
        if isinstance(value, InputNode):
            return input_value
        if isinstance(value, DAGNode):
            return value.execute(input_value)
        if isinstance(value, (list, tuple)):
            return type(value)(self._resolve(v, input_value) for v in value)
        return value

    def _resolved_args(self, input_value):
        args = [self._resolve(a, input_value) for a in self._bound_args]
        kwargs = {k: self._resolve(v, input_value)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def execute(self, input_value: Any = None):
        raise NotImplementedError

    def experimental_compile(self, buffer_size: int = 16):
        """Compile into a pre-allocated channel pipeline (reference:
        ``DAGNode.experimental_compile``, ``python/ray/dag/dag_node.py:108``)."""
        from raytpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, buffer_size=buffer_size)


class InputNode(DAGNode):
    """Placeholder for the value passed to ``dag.execute(x)``."""

    def __init__(self):
        super().__init__((), {})

    def execute(self, input_value: Any = None):
        return input_value

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    def __init__(self, remote_function, args, kwargs):
        super().__init__(args, kwargs)
        self._rf = remote_function

    def execute(self, input_value: Any = None):
        args, kwargs = self._resolved_args(input_value)
        return self._rf.remote(*args, **kwargs)


class ClassNode(DAGNode):
    def __init__(self, actor_class, args, kwargs):
        super().__init__(args, kwargs)
        self._ac = actor_class
        self._handle = None

    def execute(self, input_value: Any = None):
        if self._handle is None:
            args, kwargs = self._resolved_args(input_value)
            self._handle = self._ac.remote(*args, **kwargs)
        return self._handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundMethodNode(self, name)


class _UnboundMethodNode:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs):
        return ActorMethodNode(self._class_node, self._method_name, args, kwargs)


class ActorMethodNode(DAGNode):
    def __init__(self, handle_or_class_node, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._target = handle_or_class_node
        self._method_name = method_name

    def execute(self, input_value: Any = None):
        args, kwargs = self._resolved_args(input_value)
        target = self._target
        if isinstance(target, ClassNode):
            target = target.execute(input_value)
        return getattr(target, self._method_name).remote(*args, **kwargs)
