"""Workflow public API.

Reference analogue: ``python/ray/workflow/api.py`` — ``workflow.run`` /
``run_async``, ``resume``, ``resume_all``, ``get_status``, ``get_output``,
``list_all``, ``delete``. A workflow is a DAG of ``.bind()`` task nodes
executed with per-step durable checkpoints; resuming re-executes only the
steps that never checkpointed.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

import cloudpickle

from raytpu.dag.node import DAGNode
from raytpu.workflow.executor import WorkflowExecutor
from raytpu.workflow.storage import WorkflowStorage

_storage: Optional[WorkflowStorage] = None
_lock = threading.Lock()
_running: Dict[str, threading.Thread] = {}


def init(storage_root: Optional[str] = None) -> None:
    """Optional: choose the durable storage root before the first run."""
    global _storage
    with _lock:
        _storage = WorkflowStorage(storage_root)


def _get_storage() -> WorkflowStorage:
    global _storage
    with _lock:
        if _storage is None:
            _storage = WorkflowStorage()
        return _storage


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        workflow_input: Any = None) -> Any:
    """Execute a DAG durably; blocks and returns the output."""
    import raytpu

    if not raytpu.is_initialized():
        raytpu.init()
    storage = _get_storage()
    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:12]}"
    if workflow_id.startswith("."):
        raise ValueError(
            "workflow ids must not start with '.' (reserved for storage "
            "internals like .events)")
    if storage.get_status(workflow_id) == "SUCCESSFUL":
        return storage.load_output(workflow_id)
    storage.create_workflow(workflow_id, cloudpickle.dumps(dag),
                            workflow_input)
    return _execute_tracked(storage, workflow_id, dag, workflow_input)


def _execute_tracked(storage, workflow_id, dag, workflow_input) -> Any:
    me = threading.current_thread()
    with _lock:
        _running[workflow_id] = me
    try:
        return WorkflowExecutor(storage).execute(workflow_id, dag,
                                                 workflow_input)
    finally:
        with _lock:
            if _running.get(workflow_id) is me:
                del _running[workflow_id]


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              workflow_input: Any = None) -> str:
    """Start a workflow in the background; returns its id. The durable
    record (dag + input + RUNNING status) is written synchronously so
    get_status/get_output on the returned id never race the thread."""
    import raytpu

    if not raytpu.is_initialized():
        raytpu.init()
    storage = _get_storage()
    workflow_id = workflow_id or f"wf-{uuid.uuid4().hex[:12]}"
    if workflow_id.startswith("."):
        raise ValueError(
            "workflow ids must not start with '.' (reserved for storage "
            "internals like .events)")
    if storage.get_status(workflow_id) != "SUCCESSFUL":
        storage.create_workflow(workflow_id, cloudpickle.dumps(dag),
                                workflow_input)
        t = threading.Thread(
            target=lambda: _swallow(_execute_tracked, storage, workflow_id,
                                    dag, workflow_input),
            name=f"workflow-{workflow_id}", daemon=True,
        )
        t.start()
    return workflow_id


def _swallow(fn, *a, **kw):
    try:
        fn(*a, **kw)
    except Exception:
        pass  # status already persisted as FAILED


def resume(workflow_id: str) -> Any:
    """Re-run a stored workflow; completed steps load from checkpoints."""
    import raytpu

    if not raytpu.is_initialized():
        raytpu.init()
    storage = _get_storage()
    status = storage.get_status(workflow_id)
    if status is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    if status == "SUCCESSFUL":
        return storage.load_output(workflow_id)
    with _lock:
        live = _running.get(workflow_id)
    if live is not None and live.is_alive() and \
            live is not threading.current_thread():
        raise RuntimeError(
            f"workflow {workflow_id} is already executing in this process")
    dag = cloudpickle.loads(storage.load_dag(workflow_id))
    workflow_input = storage.load_input(workflow_id)
    storage.set_status(workflow_id, "RUNNING")
    return _execute_tracked(storage, workflow_id, dag, workflow_input)


def resume_all(include_running: bool = False) -> List[str]:
    """Resume FAILED workflows (and, with ``include_running=True``, ones
    left RUNNING by a crashed process — only safe when no other process is
    still executing them)."""
    storage = _get_storage()
    states = ("RUNNING", "FAILED") if include_running else ("FAILED",)
    resumed = []
    for meta in storage.list_workflows():
        wid = meta["workflow_id"]
        with _lock:
            live = _running.get(wid)
        if live is not None and live.is_alive():
            continue  # executing in THIS process right now
        if meta["status"] in states:
            try:
                resume(wid)
                resumed.append(wid)
            except Exception:
                pass
    return resumed


def post_event(name: str, payload: Any = None) -> None:
    """Durably deliver an external event (reference: workflow events —
    ``workflow.wait_for_event`` + event listeners). Any pending
    ``wait_for_event`` step on this name unblocks with the payload;
    late waiters see it immediately (events persist)."""
    _get_storage().post_event(name, payload)


def event_exists(name: str) -> bool:
    return _get_storage().has_event(name)


def wait_for_event(name: str, *, poll_interval_s: float = 0.2,
                   timeout_s: Optional[float] = None):
    """A DAG node that completes when the named event is posted,
    returning its payload (reference: ``workflow.wait_for_event``).
    Durable like any step: a resumed workflow whose wait already
    completed skips it; one still waiting re-enters the wait."""
    import raytpu

    root = _get_storage().root

    # num_cpus=0: a pending wait must not hold a CPU slot — N waiting
    # workflows would otherwise consume every worker and deadlock the
    # very tasks that could post the event.
    @raytpu.remote(num_cpus=0, name=f"workflow::wait_event::{name}")
    def _wait_event(_event_name: str, _root: str,
                    _poll: float, _timeout):
        import time as _time

        from raytpu.workflow import api as _api
        from raytpu.workflow.storage import WorkflowStorage

        # In-process execution shares the module: honor a root set by a
        # LATER workflow.init() (a wait built before init would bake the
        # default root). Subprocess workers fall back to the bound hint.
        storage = _api._storage or WorkflowStorage(_root)
        deadline = (None if _timeout is None
                    else _time.monotonic() + _timeout)
        while True:
            exists, payload = storage.get_event(_event_name)
            if exists:
                return payload
            if deadline is not None and _time.monotonic() >= deadline:
                raise TimeoutError(
                    f"workflow event {_event_name!r} not posted within "
                    f"{_timeout}s")
            _time.sleep(_poll)

    return _wait_event.bind(name, root, poll_interval_s, timeout_s)


def get_status(workflow_id: str) -> Optional[str]:
    return _get_storage().get_status(workflow_id)


def get_output(workflow_id: str, *, timeout: Optional[float] = None) -> Any:
    import time as _t

    storage = _get_storage()
    deadline = None if timeout is None else _t.monotonic() + timeout
    while True:
        status = storage.get_status(workflow_id)
        if status == "SUCCESSFUL":
            return storage.load_output(workflow_id)
        if status == "FAILED":
            raise RuntimeError(f"workflow {workflow_id} failed")
        if status is None:
            raise ValueError(f"no workflow {workflow_id!r}")
        if deadline is not None and _t.monotonic() >= deadline:
            raise TimeoutError(f"workflow {workflow_id} still {status}")
        _t.sleep(0.05)


def list_all() -> List[Dict[str, Any]]:
    return _get_storage().list_workflows()


def list_steps(workflow_id: str) -> List[Dict[str, Any]]:
    return _get_storage().list_steps(workflow_id)


def delete(workflow_id: str) -> None:
    _get_storage().delete_workflow(workflow_id)
