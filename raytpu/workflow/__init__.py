"""raytpu.workflow — durable DAG execution (reference: python/ray/workflow/)."""

from raytpu.workflow.api import (
    delete,
    get_output,
    get_status,
    init,
    list_all,
    list_steps,
    resume,
    resume_all,
    run,
    run_async,
    event_exists,
    post_event,
    wait_for_event,
)
from raytpu.workflow.storage import WorkflowStorage

__all__ = [
    "post_event",
    "event_exists",
    "wait_for_event",
    "WorkflowStorage", "delete", "get_output", "get_status", "init",
    "list_all", "list_steps", "resume", "resume_all", "run", "run_async",
]

from raytpu.util import usage_stats as _usage_stats

_usage_stats.record_library_usage("workflow")
