"""Workflow executor: DAG walk with per-step checkpointing.

Reference analogue: ``python/ray/workflow/workflow_executor.py`` +
``task_executor.py``: each step runs as a task; its output is checkpointed
before dependents consume it; resume loads checkpoints instead of
re-executing (exactly-once per completed step, at-least-once overall).

Step identity: the DAG position path (stable hash of function name +
argument-tree position), so resume after a crash maps checkpoints back to
the same nodes without the reference's explicit step names (which we also
accept via ``.options(name=...)`` metadata when present).
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict

from raytpu.dag.node import ActorMethodNode, DAGNode, FunctionNode, InputNode
from raytpu.workflow.storage import WorkflowStorage


class WorkflowExecutionError(Exception):
    pass


def _step_id(node: FunctionNode, path: str) -> str:
    name = getattr(getattr(node, "_rf", None), "_name", "step")
    return hashlib.sha1(f"{path}::{name}".encode()).hexdigest()[:16] \
        + "-" + name.split(".")[-1][:32]


class WorkflowExecutor:
    def __init__(self, storage: WorkflowStorage):
        self.storage = storage

    def execute(self, workflow_id: str, dag: DAGNode,
                workflow_input: Any = None) -> Any:
        """Run (or resume) the DAG; returns the final output."""
        import raytpu

        # Two phases so independent branches run CONCURRENTLY: first submit
        # the whole DAG bottom-up (checkpointed steps become inline values,
        # live steps become ObjectRefs the runtime resolves in parallel),
        # then gather + checkpoint in submission (topological) order.
        memo: Dict[int, Any] = {}          # node -> value | ObjectRef
        submitted: list = []               # (node, step_id, ref) topo order

        def submit(node: Any, path: str) -> Any:
            if isinstance(node, InputNode):
                return workflow_input
            if not isinstance(node, DAGNode):
                return node
            if isinstance(node, ActorMethodNode):
                raise WorkflowExecutionError(
                    "workflows checkpoint pure task steps; actor-method "
                    "nodes are not durable (reference: workflow steps are "
                    "tasks)"
                )
            if not isinstance(node, FunctionNode):
                raise WorkflowExecutionError(
                    f"unsupported workflow node: {type(node).__name__}")
            if id(node) in memo:
                return memo[id(node)]
            sid = _step_id(node, path)
            if self.storage.has_step(workflow_id, sid):
                value = self.storage.load_step(workflow_id, sid)
                memo[id(node)] = value
                return value
            args = [submit(a, f"{path}.a{i}")
                    for i, a in enumerate(node._bound_args)]
            kwargs = {k: submit(v, f"{path}.k{k}")
                      for k, v in node._bound_kwargs.items()}
            ref = node._rf.remote(*args, **kwargs)
            memo[id(node)] = ref
            submitted.append((node, sid, ref))
            return ref

        try:
            root = submit(dag, "r")
        except BaseException:
            self.storage.set_status(workflow_id, "FAILED")
            raise

        first_error: BaseException = None
        output = root
        for node, sid, ref in submitted:
            try:
                value = raytpu.get(ref)
            except BaseException as e:  # checkpoint the successes anyway
                if first_error is None:
                    first_error = e
                continue
            self.storage.save_step(
                workflow_id, sid,
                getattr(node._rf, "_name", "step"), value)
            if ref is root:
                output = value
        if first_error is not None:
            self.storage.set_status(workflow_id, "FAILED")
            raise first_error
        self.storage.save_output(workflow_id, output)
        self.storage.set_status(workflow_id, "SUCCESSFUL")
        return output
