"""Durable workflow storage.

Reference analogue: ``python/ray/workflow/workflow_storage.py`` — per-step
checkpointed results + workflow metadata under a filesystem root (the
reference also supports S3 via pyarrow fs; our layout keeps that door open
by going through a small FS interface). Writes are atomic
(tmp + rename) so a crash mid-write never corrupts a step result.

Layout::

    <root>/<workflow_id>/
        status.json                # RUNNING | SUCCESSFUL | FAILED | ...
        steps/<step_id>.pkl        # checkpointed step output
        steps/<step_id>.meta.json  # name, state, timestamps
        output.pkl                 # final workflow output
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

import cloudpickle

DEFAULT_ROOT = os.path.expanduser("~/.raytpu/workflows")


class WorkflowStorage:
    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get("RAYTPU_WORKFLOW_ROOT",
                                           DEFAULT_ROOT)
        os.makedirs(self.root, exist_ok=True)

    # -- paths -------------------------------------------------------------

    def _wf_dir(self, workflow_id: str) -> str:
        safe = workflow_id.replace("/", "_")
        return os.path.join(self.root, safe)

    def _steps_dir(self, workflow_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "steps")

    def _events_dir(self) -> str:
        # Dotted: can never collide with a workflow dir (ids starting
        # with '.' are rejected at run()).
        return os.path.join(self.root, ".events")

    def _event_path(self, name: str) -> str:
        # Hex encoding is injective — 'a/b' and 'a_b' must not share a
        # file (a lossy replace() cross-delivers payloads).
        return os.path.join(self._events_dir(),
                            name.encode().hex() + ".pkl")

    # -- durable events (reference: workflow event support) ----------------

    def post_event(self, name: str, payload: Any = None) -> None:
        self._atomic_write(self._event_path(name),
                           cloudpickle.dumps(payload))

    def has_event(self, name: str) -> bool:
        return os.path.exists(self._event_path(name))

    def get_event(self, name: str):
        """(exists, payload) — durable once posted."""
        try:
            with open(self._event_path(name), "rb") as f:
                return True, cloudpickle.loads(f.read())
        except FileNotFoundError:
            return False, None

    # -- atomic helpers ----------------------------------------------------

    @staticmethod
    def _atomic_write(path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- workflow level ----------------------------------------------------

    def create_workflow(self, workflow_id: str, dag_blob: bytes,
                        workflow_input: Any = None) -> None:
        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "dag.pkl"), dag_blob)
        # Input must be durable too: resume() replays with the SAME input.
        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "input.pkl"),
            cloudpickle.dumps(workflow_input))
        self.set_status(workflow_id, "RUNNING")

    def load_dag(self, workflow_id: str) -> bytes:
        with open(os.path.join(self._wf_dir(workflow_id), "dag.pkl"),
                  "rb") as f:
            return f.read()

    def load_input(self, workflow_id: str) -> Any:
        path = os.path.join(self._wf_dir(workflow_id), "input.pkl")
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return cloudpickle.loads(f.read())

    def set_status(self, workflow_id: str, status: str) -> None:
        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "status.json"),
            json.dumps({"status": status, "ts": time.time()}).encode(),
        )

    def get_status(self, workflow_id: str) -> Optional[str]:
        path = os.path.join(self._wf_dir(workflow_id), "status.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)["status"]

    def list_workflows(self) -> List[Dict[str, Any]]:
        out = []
        if not os.path.isdir(self.root):
            return out
        for wid in sorted(os.listdir(self.root)):
            status = self.get_status(wid)
            if status is not None:
                out.append({"workflow_id": wid, "status": status})
        return out

    def delete_workflow(self, workflow_id: str) -> None:
        import shutil

        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)

    # -- step level --------------------------------------------------------

    def save_step(self, workflow_id: str, step_id: str, name: str,
                  value: Any) -> None:
        self._atomic_write(
            os.path.join(self._steps_dir(workflow_id), f"{step_id}.pkl"),
            cloudpickle.dumps(value),
        )
        self._atomic_write(
            os.path.join(self._steps_dir(workflow_id),
                         f"{step_id}.meta.json"),
            json.dumps({"name": name, "state": "SUCCESSFUL",
                        "ts": time.time()}).encode(),
        )

    def has_step(self, workflow_id: str, step_id: str) -> bool:
        return os.path.exists(
            os.path.join(self._steps_dir(workflow_id), f"{step_id}.pkl"))

    def load_step(self, workflow_id: str, step_id: str) -> Any:
        with open(os.path.join(self._steps_dir(workflow_id),
                               f"{step_id}.pkl"), "rb") as f:
            return cloudpickle.loads(f.read())

    def list_steps(self, workflow_id: str) -> List[Dict[str, Any]]:
        d = self._steps_dir(workflow_id)
        out = []
        if not os.path.isdir(d):
            return out
        for fn in sorted(os.listdir(d)):
            if fn.endswith(".meta.json"):
                with open(os.path.join(d, fn)) as f:
                    meta = json.load(f)
                meta["step_id"] = fn[: -len(".meta.json")]
                out.append(meta)
        return out

    # -- output ------------------------------------------------------------

    def save_output(self, workflow_id: str, value: Any) -> None:
        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "output.pkl"),
            cloudpickle.dumps(value),
        )

    def has_output(self, workflow_id: str) -> bool:
        return os.path.exists(
            os.path.join(self._wf_dir(workflow_id), "output.pkl"))

    def load_output(self, workflow_id: str) -> Any:
        with open(os.path.join(self._wf_dir(workflow_id), "output.pkl"),
                  "rb") as f:
            return cloudpickle.loads(f.read())
