"""Trial schedulers — early stopping and population-based training.

Reference analogue: ``python/ray/tune/schedulers/`` (ASHA/HyperBand/PBT).
Decisions are made on every reported result: CONTINUE, STOP, or (PBT)
EXPLOIT another trial's config+checkpoint.
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_remove(self, trial) -> None:
        """Trial left the live set (terminated/errored/stopped/exploited)."""

    def exploit_target(self, trial):
        """PBT hook: trial to clone from (None = keep going)."""
        return None


class FIFOScheduler(TrialScheduler):
    pass


def _judge_at_rungs(rungs: List[int], rung_results: Dict[Any, List[float]],
                    rung_key, seen: set, t, value: float, rf: int,
                    max_t: int) -> str:
    """Shared successive-halving core (ASHA rung walk, HyperBand
    per-bracket rung walk): a trial reaching a rung stops unless in the
    top 1/rf of results completed there. A trial whose time_attr skips
    past a rung value is still judged at that rung — exact equality would
    silently degrade the scheduler to FIFO for trials that report every k
    iterations."""
    for rung in rungs:
        if t >= rung and rung not in seen:
            seen.add(rung)
            peers = rung_results[rung_key(rung)]
            peers.append(value)
            k = max(1, math.ceil(len(peers) / rf))
            top_k = sorted(peers, reverse=True)[:k]
            if value < top_k[-1]:
                return STOP
    if t >= max_t:
        return STOP
    return CONTINUE


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference:
    ``tune/schedulers/async_hyperband.py``): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung stops
    unless in the top 1/reduction_factor of completed results there."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 3,
                 max_t: int = 100):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        self.rung_results: Dict[int, List[float]] = defaultdict(list)
        self._completed: Dict[str, set] = defaultdict(set)

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric)
        if t is None or metric is None:
            return CONTINUE
        value = float(metric) if self.mode == "max" else -float(metric)
        return _judge_at_rungs(
            self.rungs, self.rung_results, lambda r: r,
            self._completed[trial.trial_id], t, value, self.rf, self.max_t)

    def on_trial_remove(self, trial) -> None:
        self._completed.pop(trial.trial_id, None)


class HyperBandScheduler(TrialScheduler):
    """HyperBand (reference: ``tune/schedulers/hyperband.py``): multiple
    successive-halving brackets trading off number-of-configs against
    per-config budget. Trials are assigned to brackets round-robin; each
    bracket s starts its rung ladder at ``max_t / eta^s`` and halves with
    factor eta, judged asynchronously like ASHA within the bracket (the
    reference's HB also fills brackets as trials arrive)."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: int = 3):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        # Integer arithmetic: float log truncation would silently drop the
        # cheapest bracket (e.g. log(243)/log(3) = 4.9999... -> 4).
        self.s_max = 0
        r = 1
        while r * reduction_factor <= max_t:
            r *= reduction_factor
            self.s_max += 1
        # Bracket s: rungs at max_t/eta^s, max_t/eta^(s-1), ..., max_t.
        self.brackets: List[List[int]] = []
        for s in range(self.s_max, -1, -1):
            r = max(1, max_t // (reduction_factor ** s))
            rungs = []
            while r < max_t:
                rungs.append(r)
                r *= reduction_factor
            self.brackets.append(rungs)
        self._next_bracket = 0
        self._trial_bracket: Dict[str, int] = {}
        # (bracket, rung) -> completed metric values
        self.rung_results: Dict[tuple, List[float]] = defaultdict(list)
        self._completed: Dict[str, set] = defaultdict(set)

    def _bracket_of(self, trial_id: str) -> int:
        b = self._trial_bracket.get(trial_id)
        if b is None:
            b = self._trial_bracket[trial_id] = self._next_bracket
            self._next_bracket = (self._next_bracket + 1) % len(self.brackets)
        return b

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        metric = result.get(self.metric)
        if t is None or metric is None:
            return CONTINUE
        value = float(metric) if self.mode == "max" else -float(metric)
        b = self._bracket_of(trial.trial_id)
        return _judge_at_rungs(
            self.brackets[b], self.rung_results, lambda r: (b, r),
            self._completed[trial.trial_id], t, value, self.eta,
            self.max_t)

    def on_trial_remove(self, trial) -> None:
        self._completed.pop(trial.trial_id, None)
        self._trial_bracket.pop(trial.trial_id, None)


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: ``tune/schedulers/pbt.py``): every
    ``perturbation_interval`` results, bottom-quantile trials exploit a
    top-quantile trial (config + checkpoint) and explore by perturbing
    hyperparams."""

    def __init__(self, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.rng = random.Random(seed)
        self.latest: Dict[str, float] = {}
        self._trials: Dict[str, Any] = {}

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        sign = 1.0 if self.mode == "max" else -1.0
        self.latest[trial.trial_id] = sign * float(metric)
        self._trials[trial.trial_id] = trial
        return CONTINUE

    def on_trial_remove(self, trial) -> None:
        # Quantiles must rank LIVE trials only — a dead trial left in
        # `latest` would occupy a bottom slot and shield a struggling live
        # trial from exploitation.
        self.latest.pop(trial.trial_id, None)
        self._trials.pop(trial.trial_id, None)

    def exploit_target(self, trial):
        t = trial.last_result.get(self.time_attr, 0)
        if not t or t % self.interval != 0 or len(self.latest) < 2:
            return None
        ranked = sorted(self.latest.items(), key=lambda kv: kv[1],
                        reverse=True)
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom_ids = {tid for tid, _ in ranked[-k:]}
        if trial.trial_id not in bottom_ids:
            return None
        top_ids = [tid for tid, _ in ranked[:k] if tid != trial.trial_id]
        if not top_ids:
            return None
        return self._trials[self.rng.choice(top_ids)]

    def perturb(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from raytpu.tune.search import Domain

        out = dict(config)
        for key, spec in self.mutations.items():
            if isinstance(spec, list):
                out[key] = self.rng.choice(spec)
            elif isinstance(spec, Domain):
                out[key] = spec.sample(self.rng)
            elif callable(spec):
                out[key] = spec()
            elif key in out and isinstance(out[key], (int, float)):
                out[key] = out[key] * self.rng.choice([0.8, 1.2])
        return out
