"""Tuner + trial controller.

Reference analogue: ``python/ray/tune/tuner.py:46`` (Tuner),
``tune/execution/tune_controller.py:69`` (the central event loop driving
trial actors), ``tune/trainable/``. Trials run as actors hosting the user
function in a session thread (the same session machinery Train uses — in
the reference Train itself runs *on* Tune, ``base_trainer.py:724``);
the controller polls reports, feeds the scheduler, stops/exploits trials,
and collects Results.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import raytpu
from raytpu.train.checkpoint import Checkpoint, CheckpointManager
from raytpu.train.config import Result, RunConfig
from raytpu.train.trainer import TrainWorker
from raytpu.tune.schedulers import (
    CONTINUE,
    STOP,
    FIFOScheduler,
    PopulationBasedTraining,
    TrialScheduler,
)
from raytpu.tune.search import BasicVariantGenerator, Searcher


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[Searcher] = None
    resources_per_trial: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    state: str = "PENDING"  # PENDING/RUNNING/TERMINATED/ERROR/STOPPED
    last_result: Dict[str, Any] = dataclasses.field(default_factory=dict)
    history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    actor: Any = None
    error: Optional[str] = None
    checkpoint: Optional[Checkpoint] = None
    iterations: int = 0
    # Iteration count at the latest registered checkpoint — a restored
    # trial resumes THERE, so counters/history roll back to it (reports
    # since the checkpoint will be replayed by the relaunched trial).
    ckpt_iterations: int = 0
    # True when the config came from the searcher (PBT clones don't —
    # the searcher must only see completions for ids it issued).
    from_searcher: bool = False
    # Crash-retry count (reference: FailureConfig.max_failures — a failed
    # trial restarts from its latest checkpoint instead of erroring out).
    failures: int = 0


class ResultGrid:
    def __init__(self, results: List[Result], trials: List[Trial],
                 metric: Optional[str], mode: str):
        self._results = results
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    @property
    def errors(self):
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set in TuneConfig or here)")
        best, best_v = None, None
        for r in self._results:
            if r.error is not None or metric not in r.metrics:
                continue
            v = float(r.metrics[metric])
            if best_v is None or (v > best_v if mode == "max" else v < best_v):
                best, best_v = r, v
        if best is None:
            raise RuntimeError("no successful trial reported the metric")
        return best

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for t in self._trials:
            row = dict(t.last_result)
            row["trial_id"] = t.trial_id
            row.update({f"config/{k}": v for k, v in t.config.items()
                        if isinstance(v, (int, float, str, bool))})
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Callable[[dict], None], *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        if hasattr(trainable, "train_loop_per_worker"):
            # A JaxTrainer instance: tune over its train_loop_config
            # (reference: BaseTrainer.fit wraps itself as a trainable,
            # ``base_trainer.py:724`` — the trial runs the FULL trainer,
            # gang + datasets included, not just the bare loop).
            trainer = trainable
            base_cfg = dict(trainer.train_loop_config)

            def trainable(config):  # noqa: F811
                import shutil as _sh
                import tempfile as _tf
                import uuid as _uuid

                from raytpu.train import session as session_mod

                merged = {**base_cfg, **config}
                single = (trainer.scaling_config.num_workers <= 1
                          and not trainer.datasets)
                if single:
                    # Fast path: run the loop inline so per-iteration
                    # reports stream to the trial session (ASHA/PBT see
                    # every result). Honor a user-supplied resume
                    # checkpoint when the trial isn't resuming (PBT).
                    if (trainer.resume_from_checkpoint is not None
                            and session_mod.get_checkpoint() is None):
                        s = session_mod._get_session()
                        if s is not None:
                            s.latest_checkpoint = \
                                trainer.resume_from_checkpoint
                    trainer.train_loop_per_worker(merged)
                    return
                # Unique nested run name: concurrent trials must not share
                # a run_dir (their CheckpointManagers would evict each
                # other's checkpoint_NNNNNN dirs).
                rc = trainer.run_config
                nested_name = (f"{rc.name or 'nested'}-"
                               f"{_uuid.uuid4().hex[:8]}")
                nested_rc = dataclasses.replace(rc, name=nested_name)
                nested = type(trainer)(
                    trainer.train_loop_per_worker,
                    train_loop_config=merged,
                    datasets=trainer.datasets,
                    scaling_config=trainer.scaling_config,
                    run_config=nested_rc,
                    resume_from_checkpoint=(session_mod.get_checkpoint()
                                            or trainer.resume_from_checkpoint),
                )
                result = nested.fit()
                if result.error is not None:
                    raise result.error
                # report() stages a synchronous copy, so the nested run
                # dir (manager-owned checkpoints included) can be removed
                # — otherwise every trial orphans a full run tree.
                session_mod.report(result.metrics,
                                   checkpoint=result.checkpoint)
                storage = rc.storage_path or os.path.join(
                    _tf.gettempdir(), "raytpu_results")
                _sh.rmtree(os.path.join(storage, nested_name),
                           ignore_errors=True)

        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restored: Optional[dict] = None

    @classmethod
    def restore(cls, path: str, trainable: Optional[Callable] = None
                ) -> "Tuner":
        """Resume an interrupted run from its directory (reference:
        ``Tuner.restore``, ``python/ray/tune/tuner.py:173``): finished
        trials keep their results, unfinished ones relaunch from their
        latest checkpoint, and the (pickled) searcher + scheduler continue
        from their saved state — the experiment converges to the same
        outcome as an uninterrupted run."""
        import cloudpickle

        with open(os.path.join(path, "tuner_state.pkl"), "rb") as f:
            state = cloudpickle.loads(f.read())
        rc = state["run_config"]
        rc.name = os.path.basename(os.path.normpath(path))
        rc.storage_path = os.path.dirname(os.path.normpath(path))
        tc = state["tune_config"]
        tc.search_alg = state["searcher"]
        # Through __init__ so a re-passed JaxTrainer gets the same
        # trainable-wrapping as a fresh Tuner (cls.__new__ would store the
        # raw non-callable trainer).
        tuner = cls(
            (trainable if trainable is not None
             else cloudpickle.loads(state["fn_blob"])),
            param_space=state.get("param_space") or {},
            tune_config=tc,
            run_config=rc,
        )
        tuner._restored = state
        return tuner

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        rc = self.run_config
        name = rc.name or f"raytpu-tune-{int(time.time())}"
        storage = rc.storage_path or os.path.join(
            tempfile.gettempdir(), "raytpu_results")
        run_dir = os.path.join(storage, name)
        os.makedirs(run_dir, exist_ok=True)

        searcher = tc.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples)
        scheduler = tc.scheduler or FIFOScheduler()
        if isinstance(scheduler, PopulationBasedTraining) and tc.metric:
            scheduler.metric = scheduler.metric or tc.metric

        import cloudpickle

        fn_blob = cloudpickle.dumps(self.trainable)
        max_conc = tc.max_concurrent_trials or self._default_concurrency()

        trials: List[Trial] = []
        ckpt_managers: Dict[str, CheckpointManager] = {}

        def spawn_actor(config: Dict[str, Any],
                        resume: Optional[Checkpoint] = None):
            ctx_kwargs = {"experiment_name": name, "storage_path": run_dir}
            actor = TrainWorker.options(
                resources=tc.resources_per_trial).remote(0, 1, ctx_kwargs)
            raytpu.get(actor.start.remote(
                fn_blob, config, None,
                resume.path if resume else None))
            return actor

        def launch(tid: str, config: Dict[str, Any],
                   resume: Optional[Checkpoint] = None) -> Trial:
            trial = Trial(tid, config)
            # Record the launch checkpoint: a crash BEFORE the trial's
            # first own checkpoint must retry from here (PBT exploit
            # clones would otherwise silently restart from random init).
            trial.checkpoint = resume
            trial.actor = spawn_actor(config, resume)
            trial.state = "RUNNING"
            trials.append(trial)
            return trial

        def retry_trial(trial: Trial, err: Optional[str] = None) -> None:
            """Crash retry from the latest checkpoint (reference:
            FailureConfig.max_failures): same trial identity, so
            scheduler rung statistics and the searcher's bookkeeping
            carry over; counters roll back to the checkpoint exactly as
            Tuner.restore does."""
            if trial.actor is not None:
                try:
                    raytpu.kill(trial.actor)
                except Exception:
                    pass
            trial.failures += 1
            from raytpu.util.events import record_event

            record_event(
                "WARNING", "TRIAL_RETRY",
                f"trial {trial.trial_id} crashed "
                f"(attempt {trial.failures}/"
                f"{rc.failure_config.max_failures}); restarting from "
                f"{'checkpoint' if trial.checkpoint else 'scratch'}: "
                f"{str(err)[-300:]}",
                trial_id=trial.trial_id, failures=trial.failures)
            trial.error = None
            it = trial.ckpt_iterations if trial.checkpoint else 0
            trial.iterations = it
            trial.history = list(trial.history)[:it]
            trial.last_result = (trial.history[-1] if trial.history
                                 else {})
            trial.actor = spawn_actor(trial.config, trial.checkpoint)
            trial.state = "RUNNING"

        # Open-ended searchers (TPE etc.) suggest forever; num_samples is
        # the experiment budget (reference: same num_samples semantics).
        # BasicVariantGenerator embeds its own grid x samples budget.
        budget = (searcher.total() if hasattr(searcher, "total")
                  else tc.num_samples)
        suggested = sum(1 for t in trials if t.from_searcher)

        def suggest_and_launch() -> Optional[Trial]:
            nonlocal suggested
            if budget is not None and suggested >= budget:
                return None
            tid = f"trial_{uuid.uuid4().hex[:8]}"
            cfg = searcher.suggest(tid)
            if cfg is None:
                return None
            suggested += 1
            t = launch(tid, cfg)
            t.from_searcher = True
            return t

        state_path = os.path.join(run_dir, "tuner_state.pkl")
        last_save = [0.0]

        def save_state(force: bool = False) -> None:
            """Durable experiment state (reference: experiment-state file
            the reference controller writes for Tuner.restore). Written
            atomically, throttled — the snapshot is O(total history) and
            must not dominate the 50ms polling loop."""
            now = time.monotonic()
            if not force and now - last_save[0] < 2.0:
                return
            last_save[0] = now
            blob = cloudpickle.dumps({
                "fn_blob": fn_blob,
                "param_space": self.param_space,
                "tune_config": tc,
                "run_config": rc,
                "searcher": searcher,
                "trials": [{
                    "trial_id": t.trial_id,
                    "config": t.config,
                    "state": t.state,
                    "last_result": t.last_result,
                    "history": t.history,
                    "iterations": t.iterations,
                    "ckpt_iterations": t.ckpt_iterations,
                    "error": t.error,
                    "failures": t.failures,
                    "checkpoint": (t.checkpoint.path
                                   if t.checkpoint else None),
                    "from_searcher": t.from_searcher,
                } for t in trials],
            })
            tmp = state_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, state_path)

        def finish(trial: Trial, state: str, error: Optional[str] = None):
            """Completion paths share one exit: state, actor kill (frees
            resources_per_trial), searcher + scheduler notification."""
            trial.state = state
            trial.error = error
            if trial.actor is not None:
                try:
                    raytpu.kill(trial.actor)
                except Exception:
                    pass
                trial.actor = None
            if getattr(trial, "from_searcher", False):
                searcher.on_trial_complete(trial.trial_id, trial.last_result)
            scheduler.on_trial_remove(trial)

        # Restore path: re-seat finished trials, relaunch unfinished ones
        # from their latest checkpoint.
        if self._restored is not None:
            for tr in self._restored["trials"]:
                ckpt = (Checkpoint(tr["checkpoint"])
                        if tr["checkpoint"] else None)
                if tr["state"] in ("TERMINATED", "ERROR", "STOPPED"):
                    trials.append(Trial(
                        tr["trial_id"], tr["config"], state=tr["state"],
                        last_result=tr["last_result"],
                        history=tr["history"], error=tr["error"],
                        iterations=tr["iterations"], checkpoint=ckpt,
                        from_searcher=tr["from_searcher"],
                        failures=tr.get("failures", 0)))
                else:
                    t = launch(tr["trial_id"], tr["config"], resume=ckpt)
                    # max_failures is a per-TRIAL budget; it survives
                    # experiment restores.
                    t.failures = tr.get("failures", 0)
                    # Roll back to the checkpoint point: the relaunched
                    # trial replays everything after it, so counters and
                    # history must not double-count those reports.
                    it = (tr.get("ckpt_iterations", 0) if ckpt
                          else 0)
                    t.iterations = it
                    t.ckpt_iterations = it
                    t.history = list(tr["history"])[:it]
                    t.last_result = (t.history[-1] if t.history
                                     else dict(tr["last_result"]))
                    # launch() already recorded the resume checkpoint.
                    t.from_searcher = tr["from_searcher"]
            self._restored = None
            suggested = sum(1 for t in trials if t.from_searcher)

        # Prime the first wave.
        while sum(t.state == "RUNNING" for t in trials) < max_conc:
            if suggest_and_launch() is None:
                break
        save_state(force=True)

        live = [t for t in trials if t.state == "RUNNING"]
        while live:
            polls = raytpu.get([t.actor.poll.remote() for t in live])
            for trial, (pairs, finished, err) in zip(live, polls):
                decision = CONTINUE
                for metrics, ckpt_path in pairs:
                    trial.iterations += 1
                    metrics.setdefault("training_iteration",
                                       trial.iterations)
                    trial.last_result = metrics
                    trial.history.append(metrics)
                    if ckpt_path:
                        trial.checkpoint = self._persist_ckpt(
                            ckpt_managers, run_dir, trial, ckpt_path,
                            metrics)
                        trial.ckpt_iterations = trial.iterations
                    # Model-based searchers that learn from INTERMEDIATE
                    # fidelities (BOHB) get every result, not just
                    # completions.
                    if trial.from_searcher and hasattr(searcher,
                                                       "on_trial_result"):
                        searcher.on_trial_result(trial.trial_id, metrics)
                    d = scheduler.on_result(trial, metrics)
                    if d == STOP:
                        # Later buffered results from a to-be-stopped trial
                        # must not enter rung statistics.
                        decision = STOP
                        break
                if err:
                    max_f = rc.failure_config.max_failures
                    if decision == STOP:
                        # The scheduler already cut this trial at a rung in
                        # this same poll; its decision stands (a retry
                        # could never be re-stopped — rungs are judged
                        # once).
                        finish(trial, "STOPPED")
                    elif max_f < 0 or trial.failures < max_f:
                        retry_trial(trial, err)
                    else:
                        finish(trial, "ERROR", error=err)
                    continue
                if finished:
                    finish(trial, "TERMINATED")
                    continue
                if decision == STOP:
                    finish(trial, "STOPPED")
                    continue
                # PBT exploit/explore.
                target = scheduler.exploit_target(trial)
                if target is not None and target.checkpoint is not None:
                    finish(trial, "STOPPED")
                    new_cfg = scheduler.perturb(target.config)
                    # Pin a private copy: the target's CheckpointManager
                    # may evict (rmtree) the exploited dir before the
                    # clone's lazy restore reads it.
                    pinned = self._pin_ckpt(run_dir, target.checkpoint)
                    launch(f"trial_{uuid.uuid4().hex[:8]}", new_cfg,
                           resume=pinned)
            # Rebuild from `trials` (not the poll set) so PBT clones
            # launched mid-poll stay tracked; then backfill free slots.
            live = [t for t in trials if t.state == "RUNNING"]
            while len(live) < max_conc:
                if suggest_and_launch() is None:
                    break
                live = [t for t in trials if t.state == "RUNNING"]
            save_state(force=not live)  # final snapshot is never skipped
            if live:
                time.sleep(0.05)

        # Staged-but-unregistered checkpoint snapshots (killed trials,
        # post-STOP reports) are garbage once the run ends — EXCEPT ones a
        # trial still references as its only checkpoint (a PBT clone that
        # finished before registering its own): deleting those would hand
        # the caller a Result.checkpoint pointing at nothing.
        import glob as _glob
        import shutil

        referenced = {t.checkpoint.path for t in trials
                      if t.checkpoint is not None}
        for staged in _glob.glob(os.path.join(run_dir, ".staged_ckpts",
                                              "*")):
            if staged not in referenced:
                shutil.rmtree(staged, ignore_errors=True)

        results = []
        for t in trials:
            err = None
            if t.error:
                from raytpu.core.errors import TaskError

                err = TaskError(t.trial_id, t.error)
            results.append(Result(
                metrics=t.last_result, metrics_history=t.history,
                checkpoint=t.checkpoint, path=run_dir, error=err,
                config=dict(t.config or {})))
        return ResultGrid(results, trials, tc.metric, tc.mode)

    def _pin_ckpt(self, run_dir: str, ckpt: Checkpoint) -> Checkpoint:
        import shutil

        dst = os.path.join(run_dir, ".staged_ckpts", uuid.uuid4().hex)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copytree(ckpt.path, dst)
        return Checkpoint(dst)

    def _persist_ckpt(self, managers: Dict[str, CheckpointManager],
                      run_dir: str, trial: Trial, ckpt_path: str,
                      metrics: Dict[str, Any]) -> Checkpoint:
        """Per-trial CheckpointManager so RunConfig.checkpoint_config
        (num_to_keep / score retention) is honored for tune runs the same
        way JaxTrainer.fit honors it."""
        cc = self.run_config.checkpoint_config
        mgr = managers.get(trial.trial_id)
        if mgr is None:
            mgr = managers[trial.trial_id] = CheckpointManager(
                os.path.join(run_dir, trial.trial_id),
                num_to_keep=cc.num_to_keep,
                score_attribute=cc.checkpoint_score_attribute,
                score_order=cc.checkpoint_score_order,
            )
        return mgr.register(Checkpoint(ckpt_path), metrics)

    def _default_concurrency(self) -> int:
        res = raytpu.cluster_resources()
        return max(1, int(res.get("CPU", 1)))


def run(trainable, *, param_space=None, tune_config=None, run_config=None):
    """Functional entry (reference: ``tune.run``, ``tune/tune.py:277``)."""
    return Tuner(trainable, param_space=param_space, tune_config=tune_config,
                 run_config=run_config).fit()
