"""raytpu.tune — experiment runner (reference: ``python/ray/tune/``)."""

from raytpu.train.session import report  # same report API as Train
from raytpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    PopulationBasedTraining,
    TrialScheduler,
)
from raytpu.tune.search import (
    BasicVariantGenerator,
    Searcher,
    BOHBSearcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    qrandint,
    randint,
    uniform,
)
from raytpu.tune.external import AskTellSearcher, OptunaSearcher
from raytpu.tune.tuner import ResultGrid, TuneConfig, Tuner, run

__all__ = [
    "AskTellSearcher",
    "OptunaSearcher",
    "Tuner",
    "TuneConfig",
    "ResultGrid",
    "run",
    "report",
    "choice",
    "uniform",
    "loguniform",
    "randint",
    "qrandint",
    "grid_search",
    "Searcher",
    "BasicVariantGenerator",
    "TrialScheduler",
    "FIFOScheduler",
    "ASHAScheduler",
    "HyperBandScheduler",
    "BOHBSearcher",
    "TPESearcher",
    "PopulationBasedTraining",
]

from raytpu.util import usage_stats as _usage_stats

_usage_stats.record_library_usage("tune")
