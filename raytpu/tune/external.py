"""External searcher adapters — third-party ask/tell optimizers as Tune
searchers.

Reference analogue: ``python/ray/tune/search/optuna/optuna_search.py`` (and
the Ax/HEBO siblings) — the reference wraps external optimizers behind its
``Searcher`` interface so ``TuneConfig(search_alg=...)`` accepts them
unchanged. Same shape here: :class:`AskTellSearcher` adapts any object
with ``ask() -> (token, config)`` / ``tell(token, score)``;
:class:`OptunaSearcher` binds an ``optuna`` study through it (optional
import — raises with guidance when optuna isn't installed).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from raytpu.tune.search import Domain, GridSearch, Searcher


class AskTellSearcher(Searcher):
    """Adapter for generic ask/tell optimizers.

    ``ask`` returns an opaque token plus the suggested config; ``tell``
    receives that token and the (sign-normalized: larger is better)
    score. Tune drives it through the standard Searcher surface, so
    schedulers, ``Tuner.restore`` and crash retries work unchanged.
    """

    def __init__(self, ask: Callable[[], Tuple[Any, Dict[str, Any]]],
                 tell: Callable[[Any, float], None],
                 metric: str, mode: str = "max",
                 raw_score: bool = False,
                 tell_failure: Optional[Callable[[Any], None]] = None):
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self._ask = ask
        self._tell = tell
        # Crashed/metric-less trials: the optimizer must learn the trial
        # ended (optuna would otherwise consider it running forever).
        self._tell_failure = tell_failure
        self.metric = metric
        self.mode = mode
        # raw_score: the external optimizer already knows the direction
        # (e.g. an optuna study created with direction=minimize) — hand
        # it the unnormalized metric value.
        self.raw_score = raw_score
        self._tokens: Dict[str, Any] = {}  # trial_id -> optimizer token

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        token, cfg = self._ask()
        if cfg is None:
            return None
        self._tokens[trial_id] = token
        return dict(cfg)

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any]) -> None:
        token = self._tokens.pop(trial_id, None)
        if token is None:
            return
        try:
            if self.metric in (result or {}):
                score = float(result[self.metric])
                if self.mode == "min" and not self.raw_score:
                    score = -score
                self._tell(token, score)
            elif self._tell_failure is not None:
                self._tell_failure(token)
        except Exception:
            pass  # a broken external model must not fail the run


def _optuna_distributions(param_space: Dict[str, Any], optuna) -> Dict:
    """Translate our structural Domains into optuna distributions;
    constants and custom Domains stay Tune-side."""
    dist = optuna.distributions
    out: Dict[str, Any] = {}
    for name, spec in param_space.items():
        if isinstance(spec, GridSearch):
            out[name] = dist.CategoricalDistribution(list(spec.values))
        elif isinstance(spec, Domain):
            if spec.kind == "choice":
                out[name] = dist.CategoricalDistribution(list(spec.options))
            elif spec.kind == "uniform":
                out[name] = dist.FloatDistribution(spec.low, spec.high)
            elif spec.kind == "loguniform":
                out[name] = dist.FloatDistribution(spec.low, spec.high,
                                                   log=True)
            elif spec.kind == "randint":
                out[name] = dist.IntDistribution(int(spec.low),
                                                 int(spec.high) - 1)
            elif spec.kind == "qrandint":
                lo, q = int(spec.low), int(spec.q)
                # optuna requires high to be low + k*step; randrange's
                # last reachable value is exactly that.
                hi = lo + ((int(spec.high) - 1 - lo) // q) * q
                out[name] = dist.IntDistribution(lo, hi, step=q)
            # kind == "custom": sampled Tune-side below
    return out


class OptunaSearcher(AskTellSearcher):
    """Optuna-backed searcher (reference: ``OptunaSearch``).

    Optional dependency: imports ``optuna`` at construction and raises a
    clear ImportError when absent. The study's direction follows
    ``mode``; sampler/pruner come from the caller's ``study`` (or a
    default TPE study is created).
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "max", study=None,
                 seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as e:  # pragma: no cover - env without optuna
            raise ImportError(
                "OptunaSearcher requires the 'optuna' package "
                "(pip install optuna), or use the native TPESearcher/"
                "BOHBSearcher which need no extra dependency") from e
        self._optuna = optuna
        if study is None:
            sampler = optuna.samplers.TPESampler(seed=seed)
            study = optuna.create_study(
                direction="maximize" if mode == "max" else "minimize",
                sampler=sampler)
        self._study = study
        self.param_space = dict(param_space)
        self._distributions = _optuna_distributions(param_space, optuna)
        import random as _random

        self._rng = _random.Random(seed)

        def ask():
            trial = self._study.ask(self._distributions)
            cfg = {}
            for name, spec in self.param_space.items():
                if name in self._distributions:
                    cfg[name] = trial.params[name]
                elif isinstance(spec, Domain):  # custom closure domain
                    cfg[name] = spec.sample(self._rng)
                else:  # constant
                    cfg[name] = spec
            return trial, cfg

        def tell(trial, score: float):
            self._study.tell(trial, score)

        def tell_failure(trial):
            # Reference parity: OptunaSearch reports TrialState.FAIL so
            # the sampler stops treating the trial as running.
            self._study.tell(trial, None,
                             state=optuna.trial.TrialState.FAIL)

        # raw_score: the study's direction already encodes min/max.
        super().__init__(ask, tell, metric, mode, raw_score=True,
                         tell_failure=tell_failure)
