"""Search spaces and suggestion algorithms.

Reference analogue: ``python/ray/tune/search/`` — the sample-space API
(``tune.choice/uniform/loguniform/randint/grid_search``), the
BasicVariantGenerator (grid x random expansion), and the Searcher plugin
interface the Optuna/Ax/HEBO wrappers implement.
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]
    # Structural metadata so external searcher adapters (Optuna etc.) can
    # translate the space instead of treating it as an opaque closure.
    kind: str = "custom"
    low: Optional[float] = None
    high: Optional[float] = None
    q: Optional[int] = None
    options: Optional[List[Any]] = None

    def sample(self, rng: random.Random) -> Any:
        return self.sampler(rng)


def choice(options: List[Any]) -> Domain:
    opts = list(options)
    return Domain(lambda rng: rng.choice(opts), kind="choice", options=opts)


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high), kind="uniform",
                  low=low, high=high)


def loguniform(low: float, high: float) -> Domain:
    lo, hi = math.log(low), math.log(high)
    return Domain(lambda rng: math.exp(rng.uniform(lo, hi)),
                  kind="loguniform", low=low, high=high)


def randint(low: int, high: int) -> Domain:
    return Domain(lambda rng: rng.randrange(low, high), kind="randint",
                  low=low, high=high)


def qrandint(low: int, high: int, q: int) -> Domain:
    return Domain(lambda rng: rng.randrange(low, high, q), kind="qrandint",
                  low=low, high=high, q=q)


@dataclass
class GridSearch:
    values: List[Any]


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


class Searcher:
    """Suggestion interface (reference: ``tune/search/searcher.py``)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid axes fully expanded x num_samples random draws of the rest
    (reference semantics: grid_search multiplies num_samples)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grids = [self.param_space[k].values for k in grid_keys]
        out = []
        for _ in range(self.num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                out.append(cfg)
        return out

    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator search — a real model-based
    Searcher plugin (reference plugin surface: ``tune/search/searcher.py``;
    algorithm per Bergstra et al. 2011, the estimator behind the
    reference's HyperOpt integration — implemented natively, no external
    dependency).

    Observations split into a good quantile and the rest; numeric
    dimensions are scored by a kernel-density ratio l(x)/g(x) over
    ``n_candidates`` draws; categorical dimensions by smoothed frequency
    ratios. The first ``n_startup`` suggestions are random.
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "max", n_startup: int = 8,
                 n_candidates: int = 24, gamma: float = 0.25,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.metric = metric
        self.mode = mode
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.gamma = gamma
        self.rng = random.Random(seed)
        self._live: Dict[str, Dict[str, Any]] = {}
        self._history: List[tuple] = []  # (config, score)

    def _model_ready(self) -> bool:
        return len(self._history) >= self.n_startup

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._model_ready():
            cfg = sample_config(self.param_space, self.rng)
        else:
            cfg = self._tpe_suggest()
        self._live[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any]) -> None:
        cfg = self._live.pop(trial_id, None)
        if cfg is None or self.metric not in (result or {}):
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._history.append((cfg, score))

    # -- internals ---------------------------------------------------------

    def _split(self):
        ranked = sorted(self._history, key=lambda cs: cs[1], reverse=True)
        n_good = max(1, int(len(ranked) * self.gamma))
        return ranked[:n_good], ranked[n_good:]

    def _tpe_suggest(self) -> Dict[str, Any]:
        good, bad = self._split()
        best_cfg, best_score = None, None
        for _ in range(self.n_candidates):
            cand = sample_config(self.param_space, self.rng)
            s = self._log_ratio(cand, good, bad)
            if best_score is None or s > best_score:
                best_cfg, best_score = cand, s
        return best_cfg

    def _log_ratio(self, cand, good, bad) -> float:
        total = 0.0
        for k, spec in self.param_space.items():
            x = cand[k]
            gv = [c[k] for c, _ in good if k in c]
            bv = [c[k] for c, _ in bad if k in c]
            if isinstance(x, (int, float)) and not isinstance(x, bool):
                total += math.log(self._kde(float(x), gv) + 1e-12) \
                    - math.log(self._kde(float(x), bv) + 1e-12)
            else:  # categorical: smoothed frequency ratio
                pg = (sum(1 for v in gv if v == x) + 1) / (len(gv) + 2)
                pb = (sum(1 for v in bv if v == x) + 1) / (len(bv) + 2)
                total += math.log(pg / pb)
        return total

    @staticmethod
    def _kde(x: float, values) -> float:
        vals = [float(v) for v in values
                if isinstance(v, (int, float)) and not isinstance(v, bool)]
        if not vals:
            return 1e-12
        spread = max(vals) - min(vals)
        bw = max(spread / max(1, len(vals)) if spread else abs(x) * 0.1,
                 1e-6)
        return sum(
            math.exp(-0.5 * ((x - v) / bw) ** 2) / (bw * math.sqrt(2 * math.pi))
            for v in vals) / len(vals)


def sample_config(param_space: Dict[str, Any],
                  rng: random.Random) -> Dict[str, Any]:
    cfg = {}
    for k, v in param_space.items():
        if isinstance(v, Domain):
            cfg[k] = v.sample(rng)
        elif isinstance(v, GridSearch):
            cfg[k] = rng.choice(v.values)
        else:
            cfg[k] = v
    return cfg


class BOHBSearcher(TPESearcher):
    """BOHB's model-based half (reference: ``TuneBOHB`` paired with
    ``HyperBandForBOHB``; Falkner et al. 2018): a TPE model fitted on the
    HIGHEST fidelity rung (``training_iteration``) that has enough
    observations, fed by intermediate results — the model learns from
    partial budgets long before any trial completes. Pair it with
    :class:`raytpu.tune.HyperBandScheduler`, which supplies the
    successive-halving budgets.
    """

    def __init__(self, param_space: Dict[str, Any], metric: str,
                 mode: str = "max", n_startup: int = 8,
                 n_candidates: int = 24, gamma: float = 0.25,
                 min_points_per_rung: int = 6,
                 seed: Optional[int] = None):
        super().__init__(param_space, metric, mode, n_startup,
                         n_candidates, gamma, seed)
        self.min_points_per_rung = min_points_per_rung
        # rung (iteration) -> trial_id -> (config, score)
        self._rung_obs: Dict[int, Dict[str, tuple]] = {}

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        if self.metric not in (result or {}):
            return
        cfg = self._live.get(trial_id)
        if cfg is None:
            return
        rung = int(result.get("training_iteration", 0) or 0)
        if rung <= 0:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._rung_obs.setdefault(rung, {})[trial_id] = (cfg, score)

    def _model_ready(self) -> bool:
        return (super()._model_ready()
                or any(len(v) >= self.min_points_per_rung
                       for v in self._rung_obs.values()))

    def _split(self):
        # Highest fidelity first: scores at bigger budgets dominate
        # (BOHB's core trick); pooled completions are the fallback.
        for rung in sorted(self._rung_obs, reverse=True):
            obs = list(self._rung_obs[rung].values())
            if len(obs) >= self.min_points_per_rung:
                ranked = sorted(obs, key=lambda cs: cs[1], reverse=True)
                n_good = max(1, int(len(ranked) * self.gamma))
                return ranked[:n_good], ranked[n_good:]
        return super()._split()
