"""Search spaces and suggestion algorithms.

Reference analogue: ``python/ray/tune/search/`` — the sample-space API
(``tune.choice/uniform/loguniform/randint/grid_search``), the
BasicVariantGenerator (grid x random expansion), and the Searcher plugin
interface the Optuna/Ax/HEBO wrappers implement.
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]

    def sample(self, rng: random.Random) -> Any:
        return self.sampler(rng)


def choice(options: List[Any]) -> Domain:
    opts = list(options)
    return Domain(lambda rng: rng.choice(opts))


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high))


def loguniform(low: float, high: float) -> Domain:
    lo, hi = math.log(low), math.log(high)
    return Domain(lambda rng: math.exp(rng.uniform(lo, hi)))


def randint(low: int, high: int) -> Domain:
    return Domain(lambda rng: rng.randrange(low, high))


def qrandint(low: int, high: int, q: int) -> Domain:
    return Domain(lambda rng: rng.randrange(low, high, q))


@dataclass
class GridSearch:
    values: List[Any]


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


class Searcher:
    """Suggestion interface (reference: ``tune/search/searcher.py``)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid axes fully expanded x num_samples random draws of the rest
    (reference semantics: grid_search multiplies num_samples)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> List[Dict[str, Any]]:
        grid_keys = [k for k, v in self.param_space.items()
                     if isinstance(v, GridSearch)]
        grids = [self.param_space[k].values for k in grid_keys]
        out = []
        for _ in range(self.num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg = {}
                for k, v in self.param_space.items():
                    if isinstance(v, GridSearch):
                        cfg[k] = combo[grid_keys.index(k)]
                    elif isinstance(v, Domain):
                        cfg[k] = v.sample(self.rng)
                    else:
                        cfg[k] = v
                out.append(cfg)
        return out

    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


def sample_config(param_space: Dict[str, Any],
                  rng: random.Random) -> Dict[str, Any]:
    cfg = {}
    for k, v in param_space.items():
        if isinstance(v, Domain):
            cfg[k] = v.sample(rng)
        elif isinstance(v, GridSearch):
            cfg[k] = rng.choice(v.values)
        else:
            cfg[k] = v
    return cfg
