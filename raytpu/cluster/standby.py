"""Hot-standby head: a WAL-shipping follower with lease-based election.

Reference analogue: Ray's GCS fault tolerance story (GCS + external
Redis: a restarted/failed-over GCS rehydrates from the replicated store
while raylets reconnect), crossed with the lease/epoch fencing of
classic primary-backup systems (chubby/raft leader leases): the active
head renews an epoch-stamped lease; the follower tails the head's
``GcsStore`` WAL over the ``wal_ship`` RPC into its OWN sqlite store;
when the incumbent stops proving liveness for a full lease TTL the
follower bumps the epoch, binds the serving socket, and becomes the
head with every table already warm — no restart window, no state
replay from nodes.

Split-brain safety is epoch fencing, not mutual exclusion: the elected
head's epoch (incumbent epoch + 1, from the shipped lease row) rides
every subsequent RPC; the stale incumbent — resumed from a SIGSTOP,
say — sees the higher epoch (discovery record or a stamped frame),
freezes its store, and answers everything with ``HeadRedirect``.

Liveness detection is the ship stream itself: a successful ``wal_ship``
reply IS the incumbent's lease renewal proof to this follower (the
reply carries the TTL), so there is no wall-clock comparison across
processes — only "how long since the incumbent last answered me".

The follower's cursors (per-table WAL seqs + placed-task log index)
persist in a follower-local table, so a killed-and-restarted follower
resumes tailing from its last applied offset instead of re-syncing the
world.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from raytpu.cluster import constants as tuning
from raytpu.cluster.head import (
    GcsStore,
    HeadServer,
    WAL_SHIP_TABLES,
    read_addr_record,
)
from raytpu.cluster.protocol import RpcClient
from raytpu.util import errors
from raytpu.util.failpoints import DROP, failpoint

# Follower-local state lives in its own table, NOT in a replicated one:
# a full-table resync of a shipped table must never clobber the cursors
# that say how far this follower has applied.
_LOCAL_TABLE = "standby"


class StandbyHead:
    """Follow ``head_address``, replicate its WAL into ``storage_path``,
    take over as the serving head (binding ``host:port``) when the
    incumbent's lease lapses."""

    def __init__(self, head_address: str, storage_path: str,
                 host: str = "127.0.0.1", port: int = 0,
                 addr_file: Optional[str] = None):
        self.head_address = head_address
        self.storage_path = storage_path
        self.host = host
        self.port = port
        self.addr_file = (addr_file if addr_file is not None
                          else tuning.HEAD_ADDR_FILE)
        self._store = GcsStore(storage_path)
        self._client: Optional[RpcClient] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Promotion result: the HeadServer this process becomes.
        self.head: Optional[HeadServer] = None
        self.took_over = threading.Event()
        # Shipping state (reloaded so a restarted follower resumes from
        # its cursor instead of a full resync).
        self._cursors: Dict[str, int] = {}
        self._last_epoch = 0
        self._ttl = tuning.HEAD_LEASE_TTL_S
        self._tasks_cursor = 0
        self._placed: List[Tuple[int, str, int]] = []
        self._tsdb_state: Dict[str, Any] = {}
        self._synced_once = False
        self._last_ok = time.monotonic()
        self._reload_local()

    # -- follower-local persistence ----------------------------------------

    def _reload_local(self) -> None:
        rows = self._store.load_all(_LOCAL_TABLE)
        try:
            state = json.loads(rows.get("state", b"{}"))
        except ValueError:
            state = {}
        self._cursors = {str(k): int(v) for k, v in
                         (state.get("cursors") or {}).items()}
        self._last_epoch = int(state.get("epoch", 0) or 0)
        self._ttl = float(state.get("ttl", tuning.HEAD_LEASE_TTL_S))
        self._tasks_cursor = int(state.get("tasks_cursor", 0) or 0)
        self._placed = [(int(i), str(t), int(a))
                        for i, t, a in (state.get("placed") or ())]
        self._tsdb_state = state.get("tsdb") or {}
        self._synced_once = bool(self._cursors)

    def _persist_local(self) -> None:
        self._store.put(_LOCAL_TABLE, "state", json.dumps({
            "cursors": self._cursors,
            "epoch": self._last_epoch,
            "ttl": self._ttl,
            "tasks_cursor": self._tasks_cursor,
            "placed": self._placed[-tuning.WAL_JOURNAL_MAX:],
            "tsdb": self._tsdb_state,
        }).encode())

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._follow_loop, name="standby-follow", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
        if self.head is not None:
            self.head.stop()
        elif self._store is not None:
            try:
                self._store.close()
            except Exception:
                pass

    # -- WAL tailing ---------------------------------------------------------

    def _connect(self) -> RpcClient:
        if self._client is None or self._client.closed:
            # The incumbent may have moved (we might even be following a
            # previously-elected standby): the discovery record wins
            # over the constructor address when it names a higher epoch.
            rec = read_addr_record(self.addr_file)
            if rec and int(rec.get("epoch", 0) or 0) >= self._last_epoch \
                    and rec.get("address"):
                self.head_address = str(rec["address"])
            self._client = RpcClient(self.head_address)
        return self._client

    def _follow_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client = self._connect()
                reply = client.call(
                    "wal_ship", dict(self._cursors), self._tasks_cursor,
                    # A hung (SIGSTOP'd) incumbent must not stall
                    # election: never wait longer than the lease TTL.
                    timeout=min(self._ttl,
                                tuning.CONTROL_CALL_TIMEOUT_S))
                if self._apply(reply):
                    self._synced_once = True
                self._last_ok = time.monotonic()
            except Exception as e:
                errors.swallow("standby.poll", e)
                if self._client is not None:
                    try:
                        self._client.close()
                    except Exception:
                        pass
                    self._client = None
                if self._elect():
                    return
                self._stop.wait(tuning.STANDBY_RECONNECT_DELAY_S)
                continue
            if self._elect():
                return
            self._stop.wait(tuning.WAL_SHIP_PERIOD_S)

    def _apply(self, reply: Dict[str, Any]) -> bool:
        """Fold one wal_ship reply into the local store; True iff the
        reply was applied. Cursors only advance (and persist) after the
        rows land, so a crash mid-apply re-pulls the same entries —
        applies are idempotent (puts and whole-table snaps)."""
        if failpoint("standby.apply") is DROP:
            return False  # skip the batch: cursors stay, next poll re-pulls
        epoch = int(reply.get("epoch", 0) or 0)
        if self._last_epoch and epoch != self._last_epoch:
            if epoch < self._last_epoch:
                # A not-yet-fenced stale incumbent answered: its data
                # predates state we already applied — drop the reply.
                return False
            # New head incarnation: its in-memory WAL seqs restarted, so
            # this reply was computed against our now-stale cursors (it
            # may carry deltas where a full resync is required — a
            # takeover head numbers its disk tables from seq 1). Do NOT
            # apply it: zero the cursors, persist, and let the next poll
            # pull correct full resyncs. Election is re-gated on that
            # fresh sync so we never serve a half-old-epoch replica.
            self._cursors = {}
            self._tasks_cursor = 0
            self._last_epoch = epoch
            self._synced_once = False
            self._persist_local()
            return False
        self._last_epoch = max(epoch, self._last_epoch)
        self._ttl = float(reply.get("ttl", self._ttl) or self._ttl)
        full = delta = 0
        for table, payload in (reply.get("tables") or {}).items():
            if table not in WAL_SHIP_TABLES:
                continue
            if "full" in payload:
                self._store.snapshot_table(table, payload["full"])
                full += 1
            else:
                for _seq, op, key, value in payload.get("entries", ()):
                    if op == "put":
                        self._store.put(table, key, value)
                    elif op == "del":
                        self._store.delete(table, key)
                    elif op == "snap":
                        self._store.snapshot_table(table, value)
                delta += 1
            self._cursors[table] = int(payload.get("seq", 0))
        placed_full = reply.get("placed_full")
        if placed_full is not None:
            # The head's placed journal evicted past our cursor — the
            # reply carries its whole dedup map; replace, don't merge.
            self._placed = [(int(i), str(t), int(a))
                            for i, t, a in placed_full]
        else:
            for entry in reply.get("placed") or ():
                idx, tid, att = int(entry[0]), str(entry[1]), int(entry[2])
                if idx > self._tasks_cursor:
                    self._placed.append((idx, tid, att))
        self._placed = self._placed[-tuning.WAL_JOURNAL_MAX:]
        self._tasks_cursor = max(self._tasks_cursor,
                                 int(reply.get("placed_idx", 0) or 0))
        if reply.get("tsdb"):
            self._tsdb_state = reply["tsdb"]
        self._persist_local()
        if full or delta:
            print(f"raytpu standby synced tables={full + delta} "
                  f"full={full} delta={delta}", flush=True)
        return True

    # -- election ------------------------------------------------------------

    def _elect(self) -> bool:
        """Take over iff the incumbent has not answered a ship poll for
        a full lease TTL (and we have replicated state to serve from)."""
        if self._stop.is_set() or not self._synced_once:
            return False
        if time.monotonic() - self._last_ok <= self._ttl:
            return False
        self._takeover()
        return True

    def _takeover(self) -> None:
        # kill_process here models "the standby died at the worst
        # moment": election must be re-runnable by a restarted follower.
        failpoint("standby.takeover")
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
            self._client = None
        # Hand the sqlite file to the HeadServer's own connection.
        self._store.close()
        head = HeadServer(self.host, self.port,
                          storage_path=self.storage_path,
                          addr_file=self.addr_file, takeover=True)
        # Epoch floor: the shipped lease row normally yields incumbent
        # epoch + 1; if the lease never shipped (storeless incumbent),
        # still supersede the last epoch observed on the wire.
        if head._epoch <= self._last_epoch:
            head._epoch = self._last_epoch + 1
            head._rpc.capabilities["head_epoch"] = head._epoch
        # Seed failover-dedup + TSDB sequencing state BEFORE start():
        # the pending scheduler must see the incumbent's placed log on
        # its first scan, not one poll later.
        with head._lock:
            head._placed_idx = max(head._placed_idx, self._tasks_cursor)
            for idx, tid, att in self._placed:
                head._placed[(tid, att)] = True
                head._placed_log.append((idx, tid, att))
                head._placed_idx = max(head._placed_idx, idx)
        if self._tsdb_state:
            head._metric_store.restore_seq_state(self._tsdb_state)
        addr = head.start()
        self.head = head
        self.took_over.set()
        # Same banner as head.main(): harnesses await "listening on".
        print(f"raytpu head listening on {addr}", flush=True)


def main() -> None:  # pragma: no cover - exercised via subprocess in tests
    import argparse
    import signal
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--head", required=True,
                    help="address of the active head to follow")
    ap.add_argument("--storage", required=True,
                    help="follower-local sqlite path for the replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="port to bind when taking over (0 = ephemeral)")
    ap.add_argument("--addr-file", default="",
                    help="head discovery record; read to chase the "
                         "current head, rewritten at takeover")
    args = ap.parse_args()
    standby = StandbyHead(args.head, args.storage, args.host, args.port,
                          addr_file=args.addr_file or None)
    standby.start()
    print(f"raytpu standby following {args.head}", flush=True)
    signal.sigwait({signal.SIGINT, signal.SIGTERM})
    standby.stop()
    sys.exit(0)


if __name__ == "__main__":  # pragma: no cover
    main()
