"""Single-host multi-process cluster harness for tests and local use.

Reference analogue: ``Cluster`` (``python/ray/cluster_utils.py:135``) — the
reference's primary multi-node-without-a-cluster mechanism (SURVEY.md §4
item 2): real head + node processes on one machine. ``kill_node`` is the
chaos hook (reference: ``NodeKillerActor``,
``python/ray/_private/test_utils.py:1497``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from raytpu.cluster.protocol import RpcClient


def _await_banner(proc: subprocess.Popen, marker: str, what: str,
                  max_lines: int = 50) -> str:
    """Read lines until the startup banner appears, skipping interpreter
    noise (warnings etc.); raise with everything seen if the process dies
    or never prints it."""
    seen = []
    for _ in range(max_lines):
        line = proc.stdout.readline()
        if not line:
            break
        seen.append(line)
        if marker in line:
            return line
    raise RuntimeError(
        f"{what} failed to start (rc={proc.poll()}):\n{''.join(seen)}")


class ClusterNodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id: Optional[str] = None):
        self.proc = proc
        self.node_id = node_id

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


class Cluster:
    """Launches a head process + node processes; drivers connect with
    ``raytpu.init(address=cluster.address)``."""

    def __init__(self, num_nodes: int = 0,
                 node_resources: Optional[Dict] = None,
                 host: str = "127.0.0.1",
                 head_storage: Optional[str] = None):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # Child processes must import raytpu from the same tree as us even
        # when it isn't pip-installed.
        import raytpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(raytpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        self._env = env
        self._host = host
        self._head_storage = head_storage
        self.head_proc = self._spawn_head(port=0)
        line = _await_banner(self.head_proc, "listening on", "head")
        self.address = line.strip().rsplit(" ", 1)[-1]
        self.nodes: List[ClusterNodeHandle] = []
        for _ in range(num_nodes):
            self.add_node(**(node_resources or {"num_cpus": 2}))

    def _spawn_head(self, port: int) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "raytpu.cluster.head",
               "--host", self._host, "--port", str(port)]
        if self._head_storage:
            cmd += ["--storage", self._head_storage]
        return subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=self._env,
        )

    def kill_head(self) -> None:
        """Chaos hook: SIGKILL the head process (control-plane loss)."""
        self.head_proc.kill()
        self.head_proc.wait(timeout=10)

    def restart_head(self) -> None:
        """Restart the head at the SAME address; requires head_storage for
        tables to survive (reference: GCS restart with persistent store)."""
        if self.head_proc.poll() is None:
            self.kill_head()
        port = int(self.address.rsplit(":", 1)[-1])
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            self.head_proc = self._spawn_head(port=port)
            try:
                _await_banner(self.head_proc, "listening on", "head")
                return
            except RuntimeError:
                # Port may linger in TIME_WAIT briefly after the kill.
                time.sleep(0.5)
        raise RuntimeError("head failed to restart on its old port")

    def add_node(self, num_cpus: float = 2, num_tpus: int = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None
                 ) -> ClusterNodeHandle:
        proc = subprocess.Popen(
            [sys.executable, "-m", "raytpu.cluster.node",
             "--head", self.address,
             "--num-cpus", str(num_cpus),
             "--num-tpus", str(num_tpus),
             "--resources", json.dumps(resources or {}),
             "--labels", json.dumps(labels or {}),
             "--host", self._host],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=self._env,
        )
        line = _await_banner(proc, "raytpu node", "node")
        node_id = line.split()[2]
        handle = ClusterNodeHandle(proc, node_id)
        self.nodes.append(handle)
        return handle

    def wait_for_nodes(self, count: Optional[int] = None,
                       timeout: float = 15.0) -> None:
        want = count if count is not None else len(self.nodes)
        client = RpcClient(self.address)
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                alive = [
                    n for n in client.call("list_nodes")
                    if n["alive"] and n["labels"].get("role") != "driver"
                ]
                if len(alive) >= want:
                    return
                time.sleep(0.1)
            raise TimeoutError(
                f"only {len(alive)} of {want} nodes registered")
        finally:
            client.close()

    def kill_node(self, handle: ClusterNodeHandle,
                  graceful: bool = False) -> None:
        """Chaos hook: SIGKILL (default) simulates a host loss; the head
        detects it via heartbeat timeout (reference: GcsHealthCheckManager)."""
        if graceful:
            handle.proc.send_signal(signal.SIGTERM)
        else:
            handle.proc.kill()
        handle.proc.wait(timeout=10)

    def shutdown(self) -> None:
        for n in self.nodes:
            if n.alive:
                n.proc.send_signal(signal.SIGTERM)
        for n in self.nodes:
            try:
                n.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                n.proc.kill()
        if self.head_proc.poll() is None:
            self.head_proc.send_signal(signal.SIGTERM)
            try:
                self.head_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.head_proc.kill()
