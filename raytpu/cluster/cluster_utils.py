"""Single-host multi-process cluster harness for tests and local use.

Reference analogue: ``Cluster`` (``python/ray/cluster_utils.py:135``) — the
reference's primary multi-node-without-a-cluster mechanism (SURVEY.md §4
item 2): real head + node processes on one machine. ``kill_node`` is the
chaos hook (reference: ``NodeKillerActor``,
``python/ray/_private/test_utils.py:1497``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from raytpu.cluster.protocol import RpcClient


def _await_banner(proc: subprocess.Popen, marker: str, what: str,
                  max_lines: int = 50) -> str:
    """Read lines until the startup banner appears, skipping interpreter
    noise (warnings etc.); raise with everything seen if the process dies
    or never prints it."""
    seen = []
    for _ in range(max_lines):
        line = proc.stdout.readline()
        if not line:
            break
        seen.append(line)
        if marker in line:
            return line
    raise RuntimeError(
        f"{what} failed to start (rc={proc.poll()}):\n{''.join(seen)}")


class ClusterNodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id: Optional[str] = None):
        self.proc = proc
        self.node_id = node_id

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


class Cluster:
    """Launches a head process + node processes; drivers connect with
    ``raytpu.init(address=cluster.address)``."""

    def __init__(self, num_nodes: int = 0,
                 node_resources: Optional[Dict] = None,
                 host: str = "127.0.0.1",
                 head_storage: Optional[str] = None,
                 addr_file: Optional[str] = None):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # Child processes must import raytpu from the same tree as us even
        # when it isn't pip-installed.
        import raytpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(raytpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        self._head_addr_file = addr_file
        if addr_file:
            # Every child (nodes, workers) inherits the discovery record
            # path, so redirect-on-failover works without per-process
            # configuration.
            env["RAYTPU_HEAD_ADDR_FILE"] = addr_file
        self._env = env
        self._host = host
        self._head_storage = head_storage
        self.standby_proc: Optional[subprocess.Popen] = None
        self.head_proc = self._spawn_head(port=0)
        line = _await_banner(self.head_proc, "listening on", "head")
        self.address = line.strip().rsplit(" ", 1)[-1]
        self.nodes: List[ClusterNodeHandle] = []
        for _ in range(num_nodes):
            self.add_node(**(node_resources or {"num_cpus": 2}))

    def _spawn_head(self, port: int) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "raytpu.cluster.head",
               "--host", self._host, "--port", str(port)]
        if self._head_storage:
            cmd += ["--storage", self._head_storage]
        if self._head_addr_file:
            cmd += ["--addr-file", self._head_addr_file]
        return subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=self._env,
        )

    def kill_head(self) -> None:
        """Chaos hook: SIGKILL the head process (control-plane loss)."""
        self.head_proc.kill()
        self.head_proc.wait(timeout=10)

    def pause_head(self) -> None:
        """Chaos hook: SIGSTOP the head — alive but silent past any
        lease TTL (the split-brain half of a failover test)."""
        self.head_proc.send_signal(signal.SIGSTOP)

    def resume_head(self) -> None:
        """Resume a SIGSTOP'd head; it must discover it was superseded
        and self-fence rather than keep acting as the head."""
        self.head_proc.send_signal(signal.SIGCONT)

    def add_standby(self, storage: Optional[str] = None) -> None:
        """Spawn a hot-standby head following the current head. Requires
        ``head_storage`` (the standby tails the head's WAL into its own
        replica store) and ``addr_file`` (how clients find it after
        takeover)."""
        if not self._head_storage:
            raise RuntimeError("standby requires head_storage")
        self._standby_storage = storage or f"{self._head_storage}.standby"
        self.standby_proc = self._spawn_standby()

    def _spawn_standby(self) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "raytpu.cluster.standby",
               "--head", self.address,
               "--storage", self._standby_storage,
               "--host", self._host, "--port", "0"]
        if self._head_addr_file:
            cmd += ["--addr-file", self._head_addr_file]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=self._env,
        )
        _await_banner(proc, "standby following", "standby")
        return proc

    def kill_standby(self) -> None:
        """Chaos hook: SIGKILL the follower mid-tail."""
        self.standby_proc.kill()
        self.standby_proc.wait(timeout=10)

    def restart_standby(self) -> None:
        """Respawn the follower on its existing replica store — it must
        resume WAL tailing from its persisted cursor."""
        if self.standby_proc is not None and self.standby_proc.poll() is None:
            self.kill_standby()
        self.standby_proc = self._spawn_standby()

    def await_takeover(self, timeout: float = 30.0) -> str:
        """Block until the standby takes over (it bound the serving
        socket and rewrote the discovery record); updates
        ``self.address``. Prefers polling the addr file — the standby's
        stdout goes silent while the incumbent is merely paused, and a
        blocking readline there would ignore ``timeout``."""
        deadline = time.monotonic() + timeout
        if self._head_addr_file:
            while time.monotonic() < deadline:
                if self.standby_proc.poll() is not None:
                    raise RuntimeError("standby died before takeover")
                try:
                    with open(self._head_addr_file) as f:
                        rec = json.load(f)
                except (OSError, ValueError):
                    rec = None
                if rec and rec.get("address") and \
                        rec["address"] != self.address:
                    self.address = str(rec["address"])
                    return self.address
                time.sleep(0.05)
            raise RuntimeError(
                f"standby did not take over within {timeout:g}s "
                f"(discovery record unchanged)")
        seen: List[str] = []
        while time.monotonic() < deadline:
            if self.standby_proc.poll() is not None:
                raise RuntimeError(
                    "standby died before takeover:\n" + "".join(seen))
            line = self.standby_proc.stdout.readline()
            if not line:
                time.sleep(0.05)
                continue
            seen.append(line)
            if "listening on" in line:
                self.address = line.strip().rsplit(" ", 1)[-1]
                return self.address
        raise RuntimeError(
            f"standby did not take over within {timeout:g}s:\n"
            + "".join(seen))

    def restart_head(self) -> None:
        """Restart the head at the SAME address; requires head_storage for
        tables to survive (reference: GCS restart with persistent store)."""
        if self.head_proc.poll() is None:
            self.kill_head()
        port = int(self.address.rsplit(":", 1)[-1])
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            self.head_proc = self._spawn_head(port=port)
            try:
                _await_banner(self.head_proc, "listening on", "head")
                return
            except RuntimeError:
                # Port may linger in TIME_WAIT briefly after the kill.
                time.sleep(0.5)
        raise RuntimeError("head failed to restart on its old port")

    def add_node(self, num_cpus: float = 2, num_tpus: int = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None
                 ) -> ClusterNodeHandle:
        proc = subprocess.Popen(
            [sys.executable, "-m", "raytpu.cluster.node",
             "--head", self.address,
             "--num-cpus", str(num_cpus),
             "--num-tpus", str(num_tpus),
             "--resources", json.dumps(resources or {}),
             "--labels", json.dumps(labels or {}),
             "--host", self._host],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=self._env,
        )
        line = _await_banner(proc, "raytpu node", "node")
        node_id = line.split()[2]
        handle = ClusterNodeHandle(proc, node_id)
        self.nodes.append(handle)
        return handle

    def wait_for_nodes(self, count: Optional[int] = None,
                       timeout: float = 15.0) -> None:
        want = count if count is not None else len(self.nodes)
        client = RpcClient(self.address)
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                alive = [
                    n for n in client.call("list_nodes")
                    if n["alive"] and n["labels"].get("role") != "driver"
                ]
                if len(alive) >= want:
                    return
                time.sleep(0.1)
            raise TimeoutError(
                f"only {len(alive)} of {want} nodes registered")
        finally:
            client.close()

    def kill_node(self, handle: ClusterNodeHandle,
                  graceful: bool = False) -> None:
        """Chaos hook: SIGKILL (default) simulates a host loss; the head
        detects it via heartbeat timeout (reference: GcsHealthCheckManager)."""
        if graceful:
            handle.proc.send_signal(signal.SIGTERM)
        else:
            handle.proc.kill()
        handle.proc.wait(timeout=10)

    def shutdown(self) -> None:
        for n in self.nodes:
            if n.alive:
                n.proc.send_signal(signal.SIGTERM)
        for n in self.nodes:
            try:
                n.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                n.proc.kill()
        if self.standby_proc is not None and self.standby_proc.poll() is None:
            self.standby_proc.send_signal(signal.SIGTERM)
            try:
                self.standby_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.standby_proc.kill()
        if self.head_proc.poll() is None:
            # A SIGSTOP'd head cannot handle SIGTERM; wake it first.
            try:
                self.head_proc.send_signal(signal.SIGCONT)
            except Exception:
                pass
            self.head_proc.send_signal(signal.SIGTERM)
            try:
                self.head_proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.head_proc.kill()
