"""Chunked node-to-node object transfer.

Reference analogue: the chunked pull path of
``src/ray/object_manager/object_manager.cc`` (objects move as
``chunk_size`` pieces with bounded in-flight bytes, so one multi-GiB
object cannot monopolize a connection or buffer whole in memory at the
sender). Wire surface: three RPCs served by every node —

- ``fetch_object(oid)``        → whole blob (small objects; legacy path)
- ``fetch_object_meta(oid)``   → {"size": wire_bytes} or None
- ``fetch_object_chunk(oid, off, len)`` → bytes or None (vanished)

Flow control is a process-wide BYTES-based window
(``RAYTPU_TRANSFER_WINDOW_BYTES``), shared by push and pull: aggregate
chunk payload in flight stays bounded at wire speed — the reference's
``max_bytes_in_flight`` in the pull manager — where the old count-only
semaphore let N big chunks balloon with the chunk-size knob.

Zero-copy paths (RAYTPU_ZEROCOPY, default on): :func:`fetch_object`
streams a pull straight into the local store — the receive region is
created at final size from the meta, every chunk RPC writes its range
directly into the shm mapping, and sealing publishes atomically (chunks
never accumulate in a parts list). Senders serve chunk reads through a
:class:`RangeReader` — a prefix-sum index over the wire segments built
once per transfer, returning memoryview slices of the sender's own
shm/heap buffers (or spill-file mapping) instead of a bytearray per
chunk.

Push path (reference: ``src/ray/object_manager/push_manager.h:30`` —
eager producer-to-requester streaming with bounded in-flight chunks):
:func:`push_blob` drives the receiver's ``push_object_begin`` /
``push_object_chunk`` / ``push_object_end`` RPCs with a windowed thread
pool, so a finished task's output flows to the demanding node without
per-chunk pull round-trips and a producer can offload its output before
dying.
"""

from __future__ import annotations

import bisect
import mmap
import threading
from typing import List, Optional, Union

from raytpu.core.config import cfg
from raytpu.core.ids import ObjectID
from raytpu.cluster import constants as tuning
from raytpu.runtime.serialization import SerializedValue
from raytpu.util import errors
from raytpu.util import tracing
from raytpu.util.failpoints import DROP, failpoint
from raytpu.util.resilience import Deadline


class ByteWindow:
    """Bytes-based in-flight budget (the reference pull manager's
    ``max_bytes_in_flight``). ``acquire(n)`` blocks until ``n`` more
    payload bytes fit; a request larger than the whole budget is admitted
    alone (never deadlocks a jumbo chunk), and ``release`` wakes all
    waiters so small chunks can pack the window densely."""

    def __init__(self, budget: int):
        self.budget = max(1, int(budget))
        self._used = 0
        self._cv = threading.Condition()

    def acquire(self, n: int) -> None:
        with self._cv:
            while self._used > 0 and self._used + n > self.budget:
                self._cv.wait()
            self._used += n

    def release(self, n: int) -> None:
        with self._cv:
            self._used -= n
            self._cv.notify_all()

    def in_flight(self) -> int:
        with self._cv:
            return self._used


_win: Optional[ByteWindow] = None
_win_lock = threading.Lock()


def _window() -> ByteWindow:
    """Process-wide window shared by every concurrent transfer, both
    directions — aggregate, not per-object, like the reference."""
    global _win
    with _win_lock:
        if _win is None:
            _win = ByteWindow(tuning.TRANSFER_WINDOW_BYTES)
        return _win


class RangeReader:
    """Random-access reads over an object's wire layout
    ``[4-byte header len][header][buffers…]`` without materializing it.

    The segment list and its prefix-sum offset index are built ONCE (the
    old ``read_range`` rebuilt and walked them per chunk — O(segments)
    every call); each read is a bisect plus, in the overwhelmingly common
    case of a range inside one segment, a zero-copy memoryview slice.
    """

    __slots__ = ("_segments", "_starts", "size", "_owner", "_mm")

    def __init__(self, segments: List, owner=None, mm=None):
        self._segments: List[memoryview] = []
        for s in segments:
            v = s if isinstance(s, memoryview) else memoryview(s)
            if v.format != "B":
                v = v.cast("B")
            self._segments.append(v)
        self._starts: List[int] = []
        pos = 0
        for v in self._segments:
            self._starts.append(pos)
            pos += v.nbytes
        self.size = pos
        self._owner = owner  # keeps the backing object (sv) alive
        self._mm = mm  # spill-file mapping to close()

    @classmethod
    def for_value(cls, sv: SerializedValue) -> "RangeReader":
        return cls(
            [len(sv.header).to_bytes(4, "little"), sv.header, *sv.buffers],
            owner=sv,
        )

    @classmethod
    def for_file(cls, path: str) -> "RangeReader":
        """Map a spill file (the file IS the wire layout) — chunk reads
        become slices of the mapping, one open per transfer instead of an
        open+seek+read per chunk."""
        with open(path, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        return cls([memoryview(mm)], mm=mm)

    def read(self, offset: int, length: int) -> Union[memoryview, bytes]:
        """Bytes of ``[offset, offset+length)`` clamped to the object —
        a zero-copy memoryview when the range lives in one segment."""
        end = min(offset + length, self.size)
        if offset < 0 or offset >= end:
            return b""
        i = bisect.bisect_right(self._starts, offset) - 1
        seg = self._segments[i]
        seg_off = offset - self._starts[i]
        want = end - offset
        if seg_off + want <= seg.nbytes:
            return seg[seg_off : seg_off + want]
        out = bytearray(want)
        pos = 0
        while pos < want:
            seg = self._segments[i]
            seg_off = offset + pos - self._starts[i]
            take = min(seg.nbytes - seg_off, want - pos)
            out[pos : pos + take] = seg[seg_off : seg_off + take]
            pos += take
            i += 1
        return bytes(out)

    def close(self) -> None:
        # Best-effort: a chunk slice handed to the codec may still be in
        # flight — releasing under it raises, and the GC of the last
        # slice frees the mapping anyway.
        for v in self._segments:
            try:
                v.release()
            except BufferError:
                pass
        self._segments = []
        if self._mm is not None:
            try:
                self._mm.close()
            except (BufferError, ValueError):
                pass
            self._mm = None
        self._owner = None


def wire_size(sv: SerializedValue) -> int:
    """Bytes of the flattened transfer layout (see to_bytes)."""
    return 4 + len(sv.header) + sum(len(b) for b in sv.buffers)


def read_range(sv: SerializedValue, offset: int, length: int) -> bytes:
    """Slice the flattened layout WITHOUT materializing the whole blob.
    Legacy single-shot form — a sender serving many chunks should build
    one :class:`RangeReader` and reuse it."""
    return bytes(RangeReader.for_value(sv).read(offset, length))


def _chunk_bytes() -> int:
    return max(64 * 1024, int(cfg.object_transfer_chunk_bytes))


def fetch_blob(client, oid_hex: str, timeout: Optional[float] = None,
               deadline: Optional[Deadline] = None) -> Optional[bytes]:
    """Pull one object's wire bytes from a peer, chunked when large.

    ``client`` is an RpcClient to the holding node. Returns None when the
    peer no longer holds the object. ``timeout`` bounds each chunk RPC;
    ``deadline`` bounds the whole transfer (every chunk call checks and
    shrinks to the remaining budget).

    Materializes the blob on the heap — callers that own a store should
    prefer :func:`fetch_object`, which streams into final storage.
    """
    with tracing.span("object.transfer.pull") as attrs:
        if tracing.enabled():
            attrs["oid"] = oid_hex
            attrs["peer"] = getattr(client, "address", "")
        return _fetch_blob_impl(client, oid_hex, timeout, deadline)


def _fetch_blob_impl(client, oid_hex: str, timeout: Optional[float],
                     deadline: Optional[Deadline]) -> Optional[bytes]:
    # drop => behave as if the holder no longer has the object (the
    # caller re-locates / falls back to lineage); raise models a severed
    # transfer connection.
    if failpoint("transfer.fetch.pre") is DROP:
        return None
    if timeout is None:
        timeout = tuning.FETCH_TIMEOUT_S
    chunk = _chunk_bytes()
    meta = client.call("fetch_object_meta", oid_hex, timeout=timeout,
                       deadline=deadline)
    if meta is None:
        return None
    size = int(meta["size"])
    if size <= chunk:
        return client.call("fetch_object", oid_hex, timeout=timeout,
                           deadline=deadline)
    # One final-size buffer written in place — never a parts list joined
    # at the end (that held the object twice at the worst moment).
    buf = bytearray(size)
    win = _window()
    off = 0
    while off < size:
        want = min(chunk, size - off)
        win.acquire(want)
        try:
            piece = client.call("fetch_object_chunk", oid_hex, off, want,
                                timeout=timeout, deadline=deadline)
        finally:
            win.release(want)
        if piece is None:
            return None  # holder dropped it mid-transfer; caller re-locates
        buf[off : off + len(piece)] = piece
        off += len(piece)
        if len(piece) < want:
            return None  # truncated: object changed under us
    return bytes(buf)


def fetch_object(client, oid_hex: str, store, timeout: Optional[float] = None,
                 deadline: Optional[Deadline] = None) -> bool:
    """Pull one object from a peer STRAIGHT INTO the local store.

    The zero-copy receive path: the destination (shm region or heap
    buffer) is created at final size from the peer's meta, concurrent
    windowed chunk RPCs write their ranges directly into it, and sealing
    publishes atomically. Returns True when the object is in the store.
    A failed or interrupted transfer aborts the half-written region —
    it is reclaimed, never sealed, and a retry starts clean.
    """
    with tracing.span("object.transfer.pull") as attrs:
        if tracing.enabled():
            attrs["oid"] = oid_hex
            attrs["peer"] = getattr(client, "address", "")
        return _fetch_object_impl(client, oid_hex, store, timeout, deadline)


def _fetch_object_impl(client, oid_hex: str, store,
                       timeout: Optional[float],
                       deadline: Optional[Deadline]) -> bool:
    if failpoint("transfer.fetch.pre") is DROP:
        return False
    if timeout is None:
        timeout = tuning.FETCH_TIMEOUT_S
    chunk = _chunk_bytes()
    meta = client.call("fetch_object_meta", oid_hex, timeout=timeout,
                       deadline=deadline)
    if meta is None:
        return False
    size = int(meta["size"])
    oid = ObjectID.from_hex(oid_hex)
    if size <= chunk:
        blob = client.call("fetch_object", oid_hex, timeout=timeout,
                           deadline=deadline)
        if blob is None:
            return False
        store.put(oid, SerializedValue.from_buffer(blob))
        return True
    rx = store.begin_receive(oid, size)
    win = _window()
    workers = max(1, min(8, int(cfg.object_transfer_max_concurrency)))
    failure: List[BaseException] = []

    def pull(off: int) -> bool:
        want = min(chunk, size - off)
        win.acquire(want)
        try:
            failpoint("transfer.fetch.chunk")
            piece = client.call("fetch_object_chunk", oid_hex, off, want,
                                timeout=timeout, deadline=deadline)
            if piece is None or len(piece) != want:
                return False  # vanished or truncated at the sender
            rx.write(off, piece)
            return True
        finally:
            win.release(want)

    ok = True
    from concurrent.futures import ThreadPoolExecutor

    try:
        with ThreadPoolExecutor(max_workers=workers,
                                thread_name_prefix="raytpu-pull") as ex:
            for fut in [ex.submit(pull, off)
                        for off in range(0, size, chunk)]:
                try:
                    if not fut.result():
                        ok = False
                except BaseException as e:
                    ok = False
                    failure.append(e)
        if ok:
            rx.seal()
            return True
        return False
    finally:
        rx.abort()  # no-op after seal; reclaims the region otherwise
        if failure:
            raise failure[0]  # callers key breakers off the original error


def push_blob(client, oid_hex: str, sv: SerializedValue,
              timeout: Optional[float] = None,
              deadline: Optional[Deadline] = None) -> bool:
    """Stream one object's wire bytes TO a peer node.

    Small objects ride the existing ``put_object`` RPC in one frame; large
    ones stream as bounded-in-flight chunk calls so the receiver never
    sees a partial object as stored (assembly happens receiver-side and
    only ``push_object_end`` publishes it). Returns False when the
    transfer did not complete (the receiver's pull fallback still runs).
    """
    with tracing.span("object.transfer.push") as attrs:
        if tracing.enabled():
            attrs["oid"] = oid_hex
            attrs["peer"] = getattr(client, "address", "")
        return _push_blob_impl(client, oid_hex, sv, timeout, deadline)


def _push_blob_impl(client, oid_hex: str, sv: SerializedValue,
                    timeout: Optional[float],
                    deadline: Optional[Deadline]) -> bool:
    if failpoint("transfer.push.pre") is DROP:
        return False  # push lost; receiver's pull fallback takes over
    if timeout is None:
        timeout = tuning.FETCH_TIMEOUT_S
    chunk = _chunk_bytes()
    size = wire_size(sv)
    if size <= chunk:
        client.call("put_object", oid_hex, sv.to_bytes(),  # blob-ok: small object, single wire frame by definition
                    timeout=timeout, deadline=deadline)
        return True
    if not client.call("push_object_begin", oid_hex, size, timeout=timeout,
                       deadline=deadline):
        return True  # receiver already has it (or another push is inbound)
    window = max(1, min(8, int(cfg.object_transfer_max_concurrency)))
    from concurrent.futures import ThreadPoolExecutor

    reader = RangeReader.for_value(sv)  # one index for the whole transfer
    win = _window()  # process-wide in-flight BYTES across all transfers

    def send(off: int) -> bool:
        want = min(chunk, size - off)
        win.acquire(want)
        try:
            # A memoryview slice of the sender's own storage rides into
            # the codec — no per-chunk bytearray.
            return client.call("push_object_chunk", oid_hex, off,
                               reader.read(off, want),
                               timeout=timeout, deadline=deadline) is True
        finally:
            win.release(want)

    ok = True
    try:
        with ThreadPoolExecutor(max_workers=window,
                                thread_name_prefix="raytpu-push") as ex:
            for fut in [ex.submit(send, off) for off in range(0, size, chunk)]:
                try:
                    if not fut.result():
                        ok = False
                except Exception:
                    ok = False
    finally:
        reader.close()
    if not ok:
        try:
            client.notify("push_object_abort", oid_hex)
        except Exception as e:
            errors.swallow("transfer.push_abort", e)
        return False
    return client.call("push_object_end", oid_hex, timeout=timeout,
                       deadline=deadline) is True
