"""Chunked node-to-node object transfer.

Reference analogue: the chunked pull path of
``src/ray/object_manager/object_manager.cc`` (objects move as
``chunk_size`` pieces with bounded in-flight chunks, so one multi-GiB
object cannot monopolize a connection or buffer whole in memory at the
sender). Wire surface: three RPCs served by every node —

- ``fetch_object(oid)``        → whole blob (small objects; legacy path)
- ``fetch_object_meta(oid)``   → {"size": wire_bytes} or None
- ``fetch_object_chunk(oid, off, len)`` → bytes or None (vanished)

A process-wide semaphore caps concurrent chunk fetches (reference:
``max_bytes_in_flight`` in the pull manager).

Push path (reference: ``src/ray/object_manager/push_manager.h:30`` —
eager producer-to-requester streaming with bounded in-flight chunks):
:func:`push_blob` drives the receiver's ``push_object_begin`` /
``push_object_chunk`` / ``push_object_end`` RPCs with a windowed thread
pool, so a finished task's output flows to the demanding node without
per-chunk pull round-trips and a producer can offload its output before
dying.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from raytpu.core.config import cfg
from raytpu.cluster import constants as tuning
from raytpu.runtime.serialization import SerializedValue
from raytpu.util import errors
from raytpu.util import tracing
from raytpu.util.failpoints import DROP, failpoint
from raytpu.util.resilience import Deadline

_sem: Optional[threading.Semaphore] = None
_sem_lock = threading.Lock()


def _semaphore() -> threading.Semaphore:
    global _sem
    with _sem_lock:
        if _sem is None:
            _sem = threading.Semaphore(
                max(1, int(cfg.object_transfer_max_concurrency)))
        return _sem


def wire_size(sv: SerializedValue) -> int:
    """Bytes of the flattened transfer layout (see to_bytes)."""
    return 4 + len(sv.header) + sum(len(b) for b in sv.buffers)


def read_range(sv: SerializedValue, offset: int, length: int) -> bytes:
    """Slice the flattened layout WITHOUT materializing the whole blob —
    walks the [len][header][buffers...] segments."""
    out = bytearray()
    segments: List[memoryview] = [
        memoryview(len(sv.header).to_bytes(4, "little")),
        memoryview(sv.header),
        *[memoryview(b) for b in sv.buffers],
    ]
    pos = 0
    remaining = length
    for seg in segments:
        seg_len = len(seg)
        if remaining <= 0:
            break
        if offset < pos + seg_len:
            lo = max(0, offset - pos)
            take = min(seg_len - lo, remaining)
            out += seg[lo:lo + take]
            remaining -= take
        pos += seg_len
    return bytes(out)


def fetch_blob(client, oid_hex: str, timeout: Optional[float] = None,
               deadline: Optional[Deadline] = None) -> Optional[bytes]:
    """Pull one object's wire bytes from a peer, chunked when large.

    ``client`` is an RpcClient to the holding node. Returns None when the
    peer no longer holds the object. ``timeout`` bounds each chunk RPC;
    ``deadline`` bounds the whole transfer (every chunk call checks and
    shrinks to the remaining budget).
    """
    with tracing.span("object.transfer.pull") as attrs:
        if tracing.enabled():
            attrs["oid"] = oid_hex
            attrs["peer"] = getattr(client, "address", "")
        return _fetch_blob_impl(client, oid_hex, timeout, deadline)


def _fetch_blob_impl(client, oid_hex: str, timeout: Optional[float],
                     deadline: Optional[Deadline]) -> Optional[bytes]:
    # drop => behave as if the holder no longer has the object (the
    # caller re-locates / falls back to lineage); raise models a severed
    # transfer connection.
    if failpoint("transfer.fetch.pre") is DROP:
        return None
    if timeout is None:
        timeout = tuning.FETCH_TIMEOUT_S
    chunk = max(64 * 1024, int(cfg.object_transfer_chunk_bytes))
    meta = client.call("fetch_object_meta", oid_hex, timeout=timeout,
                       deadline=deadline)
    if meta is None:
        return None
    size = int(meta["size"])
    if size <= chunk:
        return client.call("fetch_object", oid_hex, timeout=timeout,
                           deadline=deadline)
    parts: List[bytes] = []
    off = 0
    sem = _semaphore()
    while off < size:
        want = min(chunk, size - off)
        with sem:
            piece = client.call("fetch_object_chunk", oid_hex, off, want,
                                timeout=timeout, deadline=deadline)
        if piece is None:
            return None  # holder dropped it mid-transfer; caller re-locates
        parts.append(piece)
        off += len(piece)
        if len(piece) < want:
            return None  # truncated: object changed under us
    return b"".join(parts)


def push_blob(client, oid_hex: str, sv: SerializedValue,
              timeout: Optional[float] = None,
              deadline: Optional[Deadline] = None) -> bool:
    """Stream one object's wire bytes TO a peer node.

    Small objects ride the existing ``put_object`` RPC in one frame; large
    ones stream as bounded-in-flight chunk calls so the receiver never
    sees a partial object as stored (assembly happens receiver-side and
    only ``push_object_end`` publishes it). Returns False when the
    transfer did not complete (the receiver's pull fallback still runs).
    """
    with tracing.span("object.transfer.push") as attrs:
        if tracing.enabled():
            attrs["oid"] = oid_hex
            attrs["peer"] = getattr(client, "address", "")
        return _push_blob_impl(client, oid_hex, sv, timeout, deadline)


def _push_blob_impl(client, oid_hex: str, sv: SerializedValue,
                    timeout: Optional[float],
                    deadline: Optional[Deadline]) -> bool:
    if failpoint("transfer.push.pre") is DROP:
        return False  # push lost; receiver's pull fallback takes over
    if timeout is None:
        timeout = tuning.FETCH_TIMEOUT_S
    chunk = max(64 * 1024, int(cfg.object_transfer_chunk_bytes))
    size = wire_size(sv)
    if size <= chunk:
        client.call("put_object", oid_hex, sv.to_bytes(), timeout=timeout,
                    deadline=deadline)
        return True
    if not client.call("push_object_begin", oid_hex, size, timeout=timeout,
                       deadline=deadline):
        return True  # receiver already has it (or another push is inbound)
    window = max(1, min(8, int(cfg.object_transfer_max_concurrency)))
    from concurrent.futures import ThreadPoolExecutor

    sem = _semaphore()  # same process-wide in-flight cap as the pull path

    def send(off: int) -> bool:
        want = min(chunk, size - off)
        # read_range runs in the worker thread under the shared
        # semaphore: aggregate in-flight chunks across ALL transfers
        # (push and pull) stay bounded.
        with sem:
            return client.call("push_object_chunk", oid_hex, off,
                               read_range(sv, off, want),
                               timeout=timeout, deadline=deadline) is True

    ok = True
    with ThreadPoolExecutor(max_workers=window,
                            thread_name_prefix="raytpu-push") as ex:
        for fut in [ex.submit(send, off) for off in range(0, size, chunk)]:
            try:
                if not fut.result():
                    ok = False
            except Exception:
                ok = False
    if not ok:
        try:
            client.notify("push_object_abort", oid_hex)
        except Exception as e:
            errors.swallow("transfer.push_abort", e)
        return False
    return client.call("push_object_end", oid_hex, timeout=timeout,
                       deadline=deadline) is True
