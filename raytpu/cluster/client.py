"""Driver-side cluster backend: routes the core API onto head + nodes.

Reference analogue: the driver's CoreWorker talking to GCS + raylets
(``src/ray/core_worker/core_worker.cc`` submit paths). The driver is also a
data-plane peer: it embeds a serve-only :class:`NodeServer` so objects it
``put``s are fetchable by executing nodes and results it ``get``s are
pulled straight from the node that produced them.

Failure semantics (reference: owner-side ``TaskManager`` retries +
lineage): the driver tracks in-flight tasks per node; on a node-death
publish, unfinished tasks are resubmitted elsewhere if retries remain,
else their return refs resolve to ``WorkerCrashedError``. Results that
died with the node and have no other copy are re-executed (cheap lineage
reconstruction: the spec IS the lineage).
"""

from __future__ import annotations

import copy
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from raytpu.cluster import wire

from raytpu.cluster import constants as tuning
from raytpu.cluster.head import read_addr_record
from raytpu.cluster.node import NodeServer
from raytpu.cluster.protocol import ConnectionLost, HeadRedirect, RpcClient
from raytpu.core.errors import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    PlacementGroupError,
    WorkerCrashedError,
)
from raytpu.util import errors
from raytpu.util.errors import (
    CircuitOpenError,
    NodeVanishedError,
    PlacementInfeasibleError,
    RpcTimeoutError,
    TenantThrottled,
)
from raytpu.util import metrics as _metrics
from raytpu.util import profiler as _profiler
from raytpu.util import task_events
from raytpu.util import tenancy
from raytpu.util import tracing
from raytpu.util.resilience import Deadline, RetryPolicy, breaker_for
from raytpu.core.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
)
from raytpu.runtime.object_ref import ObjectRef
from raytpu.runtime.serialization import SerializedValue, serialize
from raytpu.runtime.task_spec import SchedulingKind, TaskSpec

import logging

logger = logging.getLogger(__name__)


def _ambient_task_id() -> Optional[str]:
    """The enclosing task's id when submitting from inside a worker
    (nested tasks) — the event's parent link; None from a driver."""
    try:
        from raytpu.runtime import context as _ctx

        tid = _ctx.current().task_id
        return tid.hex() if tid is not None else None
    except Exception:
        return None


class _InFlight:
    __slots__ = ("spec", "node_id", "attempts")

    def __init__(self, spec: TaskSpec, node_id: str, attempts: int = 0):
        self.spec = spec
        self.node_id = node_id
        self.attempts = attempts


class ClusterBackend:
    def __init__(self, address: str, job_id: JobID):
        if address.startswith("tcp://"):
            address = address[len("tcp://"):]
        self.job_id = job_id
        self._relay = None
        if address.startswith("raytpu://"):
            # Remote driver behind the proxy (reference: ray:// client
            # mode): one physical connection carries every logical one,
            # and the driver hosts NO serve endpoint — nodes cannot reach
            # it, so argument objects are pushed at submit time
            # (_push_local_args) instead of pulled.
            from raytpu.cluster.node import NodeBackend
            from raytpu.cluster.relay import RelayChannel

            self._relay = RelayChannel(address[len("raytpu://"):])
            self._connect = self._relay.client_for
            address = self._relay.head_address
            backend = NodeBackend(job_id, num_cpus=0, num_tpus=0,
                                  resources={})
            backend.worker.pin_owned = False  # driver owns its objects
            self._node = None
            self._driver_backend = backend
            self.node_id = NodeID.from_random()
        else:
            self._connect = RpcClient
            # Data-plane endpoint: the driver is a serve-only node.
            self._node = NodeServer(address, serve_only=True)
            self._node.start()
            self._driver_backend = self._node.backend
            self.node_id = self._node.node_id
        self._serve_address = self._node.address if self._node else None
        self.store = self._driver_backend.store
        self.worker = self._driver_backend.worker
        self.worker.job_id = job_id
        self._head_address = address
        self._head_lock = threading.Lock()
        self._head = self._connect(address)
        self._learn_epoch(self._head)
        self._subscribe_head(self._head)
        self._peers: Dict[str, RpcClient] = {}
        self._peers_lock = threading.Lock()
        self._lock = threading.RLock()
        self._inflight: Dict[TaskID, _InFlight] = {}
        self._actor_nodes: Dict[ActorID, str] = {}      # actor -> node_id
        self._actor_inflight: Dict[ActorID, List[TaskSpec]] = {}
        self._dead_actors: Dict[ActorID, str] = {}      # actor -> reason
        self._pending: List[TaskSpec] = []              # no feasible node yet
        # Admission-shed specs parked until the head's retry_after
        # elapses (ready-at monotonic time). The pending loop promotes
        # due entries back into _pending — honoring the shed instead of
        # hammering an overloaded head every poll period.
        self._throttled: List[Tuple[float, TaskSpec]] = []
        self._pgs: Dict[PlacementGroupID, dict] = {}
        self._my_actors: Dict[ActorID, bool] = {}       # actor -> detached
        # Lineage: return oid -> creating spec for plain tasks, so a result
        # whose only copy died with its node can be re-executed (reference:
        # ObjectRecoveryManager + lineage pinning, reference_count.h:61).
        self._lineage: Dict[ObjectID, Tuple[TaskSpec, int]] = {}
        # Completed-producer memory: return oids whose producing task
        # finished (inflight record released on the done event / sweep)
        # but whose value this driver never fetched. If the holding node
        # then dies, nothing else ties the ref to its fate — this map is
        # what lets the owner fail the ref instead of polling forever.
        self._done_returns: "OrderedDict[ObjectID, Tuple[Optional[ActorID], str]]" = OrderedDict()
        self._lineage_bytes = 0
        self._reconstructions: Dict[ObjectID, int] = {}
        self._reconstructing: set = set()  # TaskIDs being re-routed
        self._addr_cache: Dict[str, str] = {}  # node_id -> address
        self._shutdown_flag = False
        self._retry_thread = threading.Thread(
            target=self._pending_loop, name="cluster-pending", daemon=True
        )
        self._retry_thread.start()
        # Owner-directed distributed free: when the driver's refcount drops
        # an object, release every cluster copy (nodes pin results until
        # this arrives — reference: owner-based lifetime, A1).
        import queue as _q

        self._free_queue: "_q.Queue" = _q.Queue()
        prev_oos = self.worker.reference_counter._on_out_of_scope

        def _oos(oid):
            if prev_oos is not None:
                prev_oos(oid)
            self._free_queue.put(oid)

        self.worker.reference_counter._on_out_of_scope = _oos
        self._free_thread = threading.Thread(
            target=self._free_loop, name="cluster-free", daemon=True
        )
        self._free_thread.start()
        # Pipelined submission fast path (RAYTPU_RPC_BATCH): plain-task
        # specs enqueue into a bounded in-flight window (enqueue blocks
        # past SUBMIT_WINDOW) and a submitter thread coalesces them into
        # head submit_batch frames — only against a head that advertised
        # the capability at connect time.
        self._submit_queue: Optional["_q.Queue"] = None
        self._submit_thread: Optional[threading.Thread] = None
        if (tuning.RPC_BATCH
                and getattr(self._head, "caps", {}).get("submit_batch")):
            self._submit_queue = _q.Queue(maxsize=tuning.SUBMIT_WINDOW)
            self._submit_thread = threading.Thread(
                target=self._submit_loop, name="cluster-submit", daemon=True
            )
            self._submit_thread.start()

    # -- plumbing ----------------------------------------------------------

    def _learn_epoch(self, head: RpcClient) -> None:
        """Learn the head's epoch so subsequent frames carry it ("ep"
        stamping — a superseded head then rejects us with HeadRedirect
        instead of silently accepting writes). When batch negotiation
        already ran, the caps carry it; otherwise one explicit rpc_caps
        round trip (empty caps dict: the server stays on the unbatched
        wire). An older head without the capability just leaves frames
        unstamped."""
        try:
            caps = getattr(head, "caps", None) or head.call(
                "rpc_caps", {}, timeout=tuning.RPC_CONNECT_TIMEOUT_S)
            if isinstance(caps, dict) and caps.get("head_epoch") \
                    is not None:
                head.epoch = int(caps["head_epoch"])
        except Exception as e:
            errors.swallow("client.epoch_probe", e)

    def _subscribe_head(self, head: RpcClient) -> None:
        """Install this driver's event subscriptions on a head connection
        — at first connect AND on every reconnect (subscriptions are
        per-connection state on both sides; a restarted head knows
        nothing about the old incarnation's subscribers)."""
        head.subscribe("nodes", self._on_node_event)
        head.subscribe("actors", self._on_actor_event)
        head.subscribe("objects", self._on_object_event)
        head.subscribe("tasks", self._on_task_event)
        head.call("subscribe", "nodes")
        head.call("subscribe", "actors")
        head.call("subscribe", "objects")
        head.call("subscribe", "tasks")
        from raytpu.core.config import cfg as _cfg

        if _cfg.log_to_driver:
            head.subscribe("logs", self._on_log_event)
            head.call("subscribe", "logs")

    def _head_call(self, method: str, *args, **kw):
        """Head RPC with bounce recovery (resilience-policy seam for the
        driver): a lost connection re-dials the head address — the
        restarted head reloads its durable tables while nodes re-register
        and replay their delta buffers — then retries against the new
        incarnation. A call that raced the crash may have applied at the
        old head; every method routed through here is idempotent at the
        head or retried by a higher layer, the same contract the
        node-side reconnect already holds."""
        while True:
            head = self._head
            try:
                return head.call(method, *args, **kw)
            except HeadRedirect as r:
                # Fenced incumbent (or stale epoch): it told us where
                # the elected head lives — chase it instead of burning
                # the reconnect budget on a dead/fenced socket.
                if self._shutdown_flag:
                    raise
                if r.address:
                    self._head_address = r.address
                self._reconnect_head(head)
            except ConnectionLost:
                if self._shutdown_flag:
                    raise
                self._reconnect_head(head)

    def _reconnect_head(self, dead: RpcClient) -> None:
        """Single-flight head re-dial with exponential backoff under a
        hard deadline. Raises WorkerCrashedError when the head stays gone
        — the old terminal outcome, now only after the budget expires."""
        with self._head_lock:
            if self._head is not dead and not self._head.closed:
                return  # another caller already swapped in a live head
            deadline = Deadline.after(tuning.HEAD_RECONNECT_TIMEOUT_S)
            delay = tuning.RECONNECT_BASE_DELAY_S
            while True:
                if self._shutdown_flag:
                    raise WorkerCrashedError("shutdown during head "
                                             "reconnect")
                # Failover discovery: the record is rewritten by
                # whichever process serves as head now (a hot standby
                # publishes it the instant it takes over), so re-read it
                # every attempt — it can appear mid-backoff.
                rec = read_addr_record(tuning.HEAD_ADDR_FILE)
                if rec:
                    self._head_address = str(rec["address"])
                try:
                    head = self._connect(self._head_address)
                    self._learn_epoch(head)
                    self._subscribe_head(head)
                except Exception:
                    if deadline.expired:
                        raise WorkerCrashedError(
                            f"lost connection to cluster head; re-dial of "
                            f"{self._head_address} did not succeed within "
                            f"{tuning.HEAD_RECONNECT_TIMEOUT_S:g}s")
                    time.sleep(delay)
                    delay = min(delay * 2, tuning.RECONNECT_MAX_DELAY_S)
                    continue
                old, self._head = self._head, head
                try:
                    old.close()
                except Exception:
                    pass
                logger.info("reconnected to cluster head at %s",
                            self._head_address)
                return

    def _peer(self, address: str) -> RpcClient:
        with self._peers_lock:
            c = self._peers.get(address)
            if c is None or c.closed:
                c = self._peers[address] = self._connect(address)
            return c

    def _node_addr(self, node_id: str) -> Optional[str]:
        for n in self._head_call("list_nodes"):
            if n["node_id"] == node_id and n["alive"]:
                return n["address"]
        return None

    def _node_addr_cached(self, node_id: str) -> Optional[str]:
        """Per-element hot path (stream acks): avoid a head round-trip per
        call; entries are dropped on node-removed events."""
        with self._lock:
            addr = self._addr_cache.get(node_id)
        if addr is not None:
            return addr
        addr = self._node_addr(node_id)
        if addr is not None:
            with self._lock:
                self._addr_cache[node_id] = addr
        return addr

    def _required_resources(self, spec: TaskSpec) -> Dict[str, float]:
        return dict(spec.resources or {})

    # -- task submission ---------------------------------------------------

    def _arg_ref_ids(self, spec: TaskSpec) -> List[ObjectID]:
        return spec.arg_ref_oids()

    def _pin_args(self, spec: TaskSpec) -> None:
        """Hold submitted-task refs on the driver so argument objects can't
        be freed while a remote task still needs them (reference:
        submitted_task_ref_count, reference_count.h:607)."""
        for oid in self._arg_ref_ids(spec):
            self.worker.reference_counter.add_submitted_task_ref(oid)

    def _unpin_args(self, spec: TaskSpec) -> None:
        for oid in self._arg_ref_ids(spec):
            try:
                self.worker.reference_counter.remove_submitted_task_ref(oid)
            except Exception:
                pass

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = [ObjectRef(oid, owner=self.worker.worker_id.binary())
                for oid in spec.return_ids()]
        self._pin_args(spec)
        self._record_lineage(spec)
        # Trace root of a plain f.remote(): the head's schedule RPC and
        # the node-bound submit_task frame both parent under this span.
        with tracing.span("task.submit") as attrs:
            if tracing.enabled():
                attrs["task"] = spec.task_id.hex()[:16]
                attrs["name"] = spec.name
            # Inside the span on purpose: the emitted event captures the
            # ambient trace id, cross-linking timeline <-> chrome trace.
            if task_events.enabled():
                task_events.emit("task", spec.task_id.hex(),
                                 task_events.TaskTransition.SUBMITTED,
                                 name=spec.name, attempt=spec.attempt,
                                 parent_task_id=_ambient_task_id())
            if (self._submit_queue is not None
                    and spec.scheduling.kind == SchedulingKind.DEFAULT
                    and spec.actor_id is None
                    and not spec.is_actor_creation()):
                # Fast path: refs return now; the submitter thread batches
                # the window into submit_batch frames. Per-spec failures
                # surface through ref resolution (_fail_refs), exactly
                # like the pending-loop's asynchronous errors.
                self._submit_queue.put(spec)
            else:
                # PG / affinity / actor specs keep the per-spec path: its
                # synchronous errors (PlacementGroupError) are part of
                # the API contract.
                self._route_task(spec)
        return refs

    def _record_lineage(self, spec: TaskSpec) -> None:
        """Remember the creating spec of each return object (plain tasks
        only — actor method outputs depend on actor state and are not
        reconstructible; reference: same restriction)."""
        from raytpu.core.config import cfg

        if spec.actor_id is not None or spec.is_actor_creation():
            return
        per_oid = (len(spec.function_blob)
                   + sum(len(a.data) for a in spec.args)
                   + 256) // max(1, spec.num_returns) + 1
        # Store a private copy: the submitted spec is mutated by the retry
        # path (`attempt += 1` in _on_node_event) and must not race with
        # the lineage record a later reconstruction re-routes.
        stored = copy.copy(spec)
        with self._lock:
            for oid in spec.return_ids():
                self._lineage[oid] = (stored, per_oid)
                self._lineage_bytes += per_oid
            # FIFO eviction beyond the lineage budget (reference:
            # max_lineage_bytes, task_manager.h:210).
            budget = int(cfg.max_lineage_bytes)
            while self._lineage_bytes > budget and self._lineage:
                old_oid = next(iter(self._lineage))
                _, old_size = self._lineage.pop(old_oid)
                self._lineage_bytes -= old_size

    def _reconstruct(self, oid: ObjectID) -> bool:
        """Re-execute the task that created a lost object (reference:
        ``ObjectRecoveryManager::RecoverObject``). Returns True if a
        re-execution was started (or is already running)."""
        with self._lock:
            entry = self._lineage.get(oid)
            if entry is None:
                return False
            stored = entry[0]
            # The _reconstructing guard holds the dedupe from this check
            # until _route_task has registered the task inflight/pending,
            # so two threads (get_object poll + objects pubsub) can't both
            # route the same task.
            if (stored.task_id in self._inflight
                    or stored.task_id in self._reconstructing
                    or any(s.task_id == stored.task_id
                           for s in self._pending)):
                return True  # already being produced
            n = self._reconstructions.get(oid, 0)
            if n >= 3:
                return False
            self._reconstructions[oid] = n + 1
            self._reconstructing.add(stored.task_id)
        # Route a fresh copy: the stored lineage spec stays immutable so
        # concurrent reconstructions / retries never share mutable state.
        spec = copy.copy(stored)
        spec.attempt = stored.attempt + n + 1
        self._pin_args(spec)
        try:
            self._route_task(spec)
        except Exception:
            self._unpin_args(spec)
            return False
        finally:
            with self._lock:
                self._reconstructing.discard(stored.task_id)
        return True

    def _route_task(self, spec: TaskSpec) -> None:
        node_id = self._pick_node(spec)
        if node_id is None:
            with self._lock:
                self._pending.append(spec)
            if task_events.enabled():
                task_events.emit("task", spec.task_id.hex(),
                                 task_events.TaskTransition.PENDING_SCHED,
                                 name=spec.name, attempt=spec.attempt)
            return
        self._send_to_node(spec, node_id, "submit_task")

    def _pick_node(self, spec: TaskSpec) -> Optional[str]:
        sched = spec.scheduling
        if sched.kind == SchedulingKind.PLACEMENT_GROUP and sched.pg_id:
            pg = self._pgs.get(sched.pg_id) or \
                self._head_call("pg_info", sched.pg_id.hex())
            if pg is None:
                raise PlacementGroupError(
                    f"placement group {sched.pg_id.hex()} gone")
            idx = sched.bundle_index if sched.bundle_index >= 0 else 0
            node_id = pg["nodes"][idx]
            return node_id
        # Arg oids let the head score feasible nodes by the bytes they
        # already hold (appended param — older heads ignore it).
        # The tenant rides the frame ("tn"), not the args, and this call
        # often runs on a background thread (pending loop, lineage
        # reconstruction) whose ambient tenant is empty — re-anchor from
        # the spec so retries book against the submitting tenant instead
        # of arriving untenanted and bypassing its quota.
        if spec.tenant:
            with tenancy.tenant_scope(spec.tenant):
                return self._head_call(
                    "schedule", self._required_resources(spec), None, 0.5,
                    spec.task_id.hex(),
                    [o.hex() for o in spec.arg_ref_oids()])
        return self._head_call(
            "schedule", self._required_resources(spec), None, 0.5,
            spec.task_id.hex(), [o.hex() for o in spec.arg_ref_oids()])

    def _ship_runtime_env(self, spec: TaskSpec, addr: str) -> None:
        """Push packaged zip:// URIs to the executing node's cache before
        the task lands there (reference: runtime-env agent fetch)."""
        renv = spec.runtime_env or {}
        uris = []
        for key in ("working_dir", "py_modules"):
            v = renv.get(key)
            if isinstance(v, str):
                v = [v]
            uris.extend(u for u in (v or ()) if isinstance(u, str)
                        and u.startswith("zip://"))
        if not uris:
            return
        from raytpu.runtime_env import read_blob

        peer = self._peer(addr)
        for uri in uris:  # rpc-loop-ok: runtime-env zips: few URIs, bulk payloads
            try:
                if not peer.call("has_runtime_env", uri):
                    peer.call("cache_runtime_env", uri, read_blob(uri))
            except FileNotFoundError:
                pass  # not packaged locally either; task will surface it

    def _send_to_node(self, spec: TaskSpec, node_id: str,
                      method: str) -> None:
        addr = self._node_addr(node_id)
        if addr is None:
            with self._lock:
                self._pending.append(spec)
            return
        try:
            self._ship_runtime_env(spec, addr)
        except Exception:
            pass
        if self._relay is not None:
            self._push_local_args(spec, addr)
        with self._lock:
            self._inflight[spec.task_id] = _InFlight(
                spec, node_id, attempts=spec.attempt)
        try:
            self._peer(addr).call(method, wire.dumps(spec))
        except Exception:
            with self._lock:
                self._inflight.pop(spec.task_id, None)
                self._pending.append(spec)
            if task_events.enabled():
                task_events.emit("task", spec.task_id.hex(),
                                 task_events.TaskTransition.PENDING_SCHED,
                                 name=spec.name, attempt=spec.attempt,
                                 error="node submit failed; requeued")

    def _submit_loop(self) -> None:
        """Submitter thread: drains the bounded window, coalescing up to
        SUBMIT_BATCH_MAX specs per head round trip (FIFO preserved)."""
        import queue as _q

        q = self._submit_queue
        while True:
            try:
                spec = q.get(timeout=tuning.PENDING_POLL_PERIOD_S)
            except _q.Empty:
                if self._shutdown_flag:
                    return
                continue
            if spec is None:
                return
            batch = [spec]
            while len(batch) < tuning.SUBMIT_BATCH_MAX:
                try:
                    nxt = q.get_nowait()
                except _q.Empty:
                    break
                if nxt is None:
                    self._flush_submit(batch)
                    return
                batch.append(nxt)
            self._flush_submit(batch)

    def _flush_submit(self, specs: List[TaskSpec]) -> None:
        """One pipelined round: place the whole batch with one head RPC,
        group placements by node, ship one submit_batch frame per node."""
        try:
            placements = self._head.call("submit_batch",
                                         wire.dumps(list(specs)))
        except Exception:
            # Head unreachable this round: everything requeues as pending
            # (the pending loop retries; node-death semantics unchanged).
            with self._lock:
                self._pending.extend(specs)
            if task_events.enabled():
                for spec in specs:
                    task_events.emit(
                        "task", spec.task_id.hex(),
                        task_events.TaskTransition.PENDING_SCHED,
                        name=spec.name, attempt=spec.attempt,
                        error="submit_batch failed; requeued")
            return
        by_node: Dict[Tuple[str, str], List[TaskSpec]] = {}
        for spec, p in zip(specs, placements):
            if isinstance(p, dict) and p.get("err"):
                self._fail_refs(spec, RuntimeError(p["err"]))
                continue
            if isinstance(p, dict) and p.get("throttled") is not None:
                # Admission control shed this spec: park it until the
                # head's retry_after elapses, then resubmit — never
                # fail it (TenantThrottled is retryable by contract).
                self._defer_throttled(spec, p.get("throttled"))
                continue
            if isinstance(p, dict) and p.get("queued"):
                # The head owns this spec now (durably when storage is
                # on): its pending scheduler dispatches it when capacity
                # appears — even if this driver spends the whole wait
                # blocked in get() across a head bounce. Track it in
                # flight (no node yet) so the completion sweep still
                # releases the submitted-arg pins.
                with self._lock:
                    self._inflight[spec.task_id] = _InFlight(
                        spec, "", attempts=spec.attempt)
                continue
            if (not isinstance(p, dict) or not p.get("node_id")
                    or not p.get("address")):
                with self._lock:
                    self._pending.append(spec)
                if task_events.enabled():
                    task_events.emit(
                        "task", spec.task_id.hex(),
                        task_events.TaskTransition.PENDING_SCHED,
                        name=spec.name, attempt=spec.attempt)
                continue
            by_node.setdefault((p["node_id"], p["address"]),
                               []).append(spec)
        for (node_id, addr), group in by_node.items():
            self._send_batch_to_node(group, node_id, addr)

    def _send_batch_to_node(self, specs: List[TaskSpec], node_id: str,
                            addr: str) -> None:
        for spec in specs:
            try:
                self._ship_runtime_env(spec, addr)
            except Exception:
                pass
            if self._relay is not None:
                self._push_local_args(spec, addr)
        with self._lock:
            for spec in specs:
                self._inflight[spec.task_id] = _InFlight(
                    spec, node_id, attempts=spec.attempt)
        try:
            peer = self._peer(addr)
            if getattr(peer, "caps", {}).get("submit_batch"):
                peer.call("submit_batch", wire.dumps(list(specs)))
            else:
                # rpc-loop-ok: mixed-version fallback — this peer never
                # advertised submit_batch, so each spec ships alone.
                for spec in specs:  # rpc-loop-ok: mixed-version fallback: peer lacks submit_batch
                    peer.call("submit_task", wire.dumps(spec))
        except Exception:
            with self._lock:
                for spec in specs:
                    self._inflight.pop(spec.task_id, None)
                    self._pending.append(spec)
            if task_events.enabled():
                for spec in specs:
                    task_events.emit(
                        "task", spec.task_id.hex(),
                        task_events.TaskTransition.PENDING_SCHED,
                        name=spec.name, attempt=spec.attempt,
                        error="node submit failed; requeued")

    def _push_local_args(self, spec: TaskSpec, addr: str) -> None:
        """Proxy-mode drivers host no serve endpoint, so nodes cannot pull
        argument objects from them — ship driver-local args to the
        executing node with the submission (reference contrast: ray://
        keeps the driver's objects server-side instead)."""
        peer = self._peer(addr)
        for oid in self._arg_ref_ids(spec):  # rpc-loop-ok: proxy-mode arg push: bulk blobs, few refs
            sv = self.store.try_get(oid)
            if sv is None:
                continue  # produced cluster-side; node pulls normally
            try:
                if peer.call("has_object", oid.hex()):
                    continue
                from raytpu.cluster.transfer import push_blob

                # Small args ride one put_object frame; large ones stream
                # as windowed chunks read off the driver's own buffers —
                # the arg is never flattened into a second driver-side
                # copy.
                if not push_blob(peer, oid.hex(), sv):
                    raise ConnectionError("push did not complete")
            except Exception as e:
                # The task will fail node-side with a missing-object pull
                # error; leave a trail pointing at the real cause.
                logger.warning("push of driver-local arg %s to %s failed: "
                               "%s", oid.hex()[:12], addr, e)

    def _free_loop(self) -> None:
        # Head-mediated free (borrower protocol): the head defers the free
        # while any worker still borrows the ref, and fires it on the last
        # borrow_released / borrower death (reference: the owner's free
        # waits on WaitForRefRemoved replies from borrowers).
        while not self._shutdown_flag:
            oid = self._free_queue.get()
            if oid is None or self._shutdown_flag:
                return
            try:
                self._head.call("request_free", oid.hex(),
                                timeout=tuning.CONTROL_CALL_TIMEOUT_S)
            except Exception as e:
                errors.swallow("client.free_loop", e)

    def _defer_throttled(self, spec: TaskSpec, retry_after_s) -> None:
        """Park an admission-shed spec until the head's retry_after
        elapses; the pending loop promotes it back then."""
        delay = max(float(retry_after_s or 0.0),
                    tuning.TENANT_RETRY_DELAY_S)
        with self._lock:
            self._throttled.append((time.monotonic() + delay, spec))
        if task_events.enabled():
            task_events.emit("task", spec.task_id.hex(),
                             task_events.TaskTransition.PENDING_SCHED,
                             name=spec.name, attempt=spec.attempt,
                             error=f"tenant throttled; retry in "
                                   f"{delay:.3f}s")

    def _pending_loop(self) -> None:
        while not self._shutdown_flag:
            time.sleep(tuning.PENDING_POLL_PERIOD_S)
            now = time.monotonic()
            with self._lock:
                if self._throttled:
                    due = [s for t, s in self._throttled if t <= now]
                    self._throttled = [(t, s) for t, s in self._throttled
                                       if t > now]
                    self._pending.extend(due)
                pending, self._pending = self._pending, []
            for spec in pending:
                if self._shutdown_flag:
                    return
                try:
                    self._route_task(spec)
                except TenantThrottled as e:
                    self._defer_throttled(spec, e.retry_after_s)
                except Exception as e:
                    self._fail_refs(spec, e)
            self._sweep_completed()

    def _sweep_completed(self) -> None:
        """Detect finished tasks (all return objects exist somewhere) and
        release their submitted-arg pins + inflight records."""
        with self._lock:
            candidates = list(self._inflight.values())
        for rec in candidates:  # rpc-loop-ok: background sweep, head-gated, not submit path
            oids = rec.spec.return_ids()
            try:
                done = all(self.store.contains(oid) or
                           bool(self._head.call(
                               "locate_object", oid.hex(),
                               timeout=tuning.CONTROL_CALL_TIMEOUT_S))
                           for oid in oids)
            except Exception:
                continue
            if done:
                with self._lock:
                    # Unpin only if WE removed the record — the task_done
                    # pubsub path may have already popped and unpinned it;
                    # a second unpin would double-decrement the submitted
                    # refs shared with other in-flight tasks.
                    popped = self._inflight.pop(rec.spec.task_id, None)
                    if popped is not None and rec.spec.actor_id is not None:
                        lst = self._actor_inflight.get(rec.spec.actor_id)
                        if lst and rec.spec in lst:
                            lst.remove(rec.spec)
                    if popped is not None:
                        self._record_done_return(rec.spec, rec.node_id)
                if popped is not None:
                    self._unpin_args(popped.spec)

    # -- actors ------------------------------------------------------------

    def create_actor(self, spec: TaskSpec) -> None:
        ac = spec.actor_creation

        def _place() -> Tuple[str, str]:
            # _pick_node honors placement-group scheduling (bundle ->
            # node); a bare schedule call here would strand PG-placed
            # actors on arbitrary nodes whose bundle shard they cannot
            # reserve.
            node_id = self._pick_node(spec)
            if node_id is None:
                raise ValueError(
                    f"no feasible node for actor "
                    f"{ac.name or ac.actor_id.hex()} "
                    f"requiring {spec.resources}")
            addr = self._node_addr(node_id)
            if addr is None:
                # Scheduler raced with failure detection: typed and
                # retryable, so the policy below re-picks a live node
                # (the old signal was ValueError("...; retry") that
                # nothing actually retried).
                raise NodeVanishedError(node_id)
            return node_id, addr

        node_id, addr = RetryPolicy(seed=0).run(
            _place, what=f"place actor {ac.actor_id.hex()[:12]}")
        with self._lock:
            self._actor_nodes[ac.actor_id] = node_id
            self._my_actors[ac.actor_id] = bool(ac.lifetime_detached)
        try:
            self._ship_runtime_env(spec, addr)
        except Exception:
            pass
        if self._relay is not None:
            self._push_local_args(spec, addr)
        self._peer(addr).call("create_actor", wire.dumps(spec))

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = [ObjectRef(oid, owner=self.worker.worker_id.binary())
                for oid in spec.return_ids()]
        with self._lock:
            dead = self._dead_actors.get(spec.actor_id)
        if dead is not None:
            self._fail_refs(spec, ActorDiedError(spec.actor_id.hex(), dead))
            return refs
        node_id = None
        with self._lock:
            node_id = self._actor_nodes.get(spec.actor_id)
        if node_id is None:
            # Resolve via the head; if the head is mid-restart, wait for
            # the new incarnation instead of failing (reference: client
            # submissions buffer while GCS restarts an actor).
            deadline = Deadline.after(tuning.ACTOR_RESOLVE_TIMEOUT_S)
            while True:
                info = self._head_call("resolve_actor", spec.actor_id.hex())
                if info is not None and info.get("state") == "alive":
                    break
                with self._lock:
                    dead = self._dead_actors.get(spec.actor_id)
                if dead is not None or info is None:
                    self._fail_refs(spec, ActorDiedError(
                        spec.actor_id.hex(), dead or "actor not found"))
                    return refs
                if deadline.expired:
                    self._fail_refs(spec, ActorDiedError(
                        spec.actor_id.hex(),
                        f"actor stuck restarting for "
                        f"{tuning.ACTOR_RESOLVE_TIMEOUT_S:g}s"))
                    return refs
                time.sleep(tuning.RESTART_POLL_PERIOD_S)
            node_id = info["node_id"]
            with self._lock:
                self._actor_nodes[spec.actor_id] = node_id
        addr = self._node_addr(node_id)
        if addr is None:
            self._fail_refs(spec, ActorDiedError(
                spec.actor_id.hex(), "actor node is gone"))
            return refs
        self._pin_args(spec)
        with self._lock:
            self._actor_inflight.setdefault(spec.actor_id, []).append(spec)
            self._inflight[spec.task_id] = _InFlight(spec, node_id)
        if self._relay is not None:
            self._push_local_args(spec, addr)
        try:
            self._peer(addr).call("submit_actor_task",
                                  wire.dumps(spec))
        except Exception as e:
            self._fail_refs(spec, ActorDiedError(spec.actor_id.hex(), str(e)))
        return refs

    def get_actor_handle_info(self, name: str, namespace: str):
        info = self._head_call("resolve_named_actor", name, namespace)
        if info is None:
            raise ValueError(f"no actor named {name!r} in {namespace!r}")
        blob = self._head_call(
            "kv_get", f"__actor_spec__::{info['actor_id']}")
        if blob is None:
            raise ValueError(f"actor {name!r} spec not found")
        spec: TaskSpec = wire.loads(blob)
        actor_id = ActorID.from_hex(info["actor_id"])
        # Mid-restart lookups have no node yet; submission resolves the
        # new incarnation's location via resolve_actor.
        node_id = info.get("node_id")
        if node_id is not None:
            with self._lock:
                self._actor_nodes[actor_id] = node_id
        return actor_id, spec

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        with self._lock:
            node_id = self._actor_nodes.get(actor_id)
        if node_id is None:
            info = self._head_call("resolve_actor", actor_id.hex())
            if info is None:
                return
            node_id = info["node_id"]
        addr = self._node_addr(node_id)
        if addr is not None:
            try:
                self._peer(addr).call("kill_actor", actor_id.hex(),
                                      no_restart)
            except Exception as e:
                errors.swallow("client.kill_actor", e)

    def actor_handle_added(self, actor_id: ActorID) -> None:
        pass  # cluster actors live until killed or their node dies

    def actor_handle_removed(self, actor_id: ActorID) -> None:
        pass

    # -- streaming generators ----------------------------------------------

    def _stream_notify(self, method: str, task_id: TaskID,
                       count: int) -> None:
        with self._lock:
            rec = self._inflight.get(task_id)
        if rec is not None:
            addr = self._node_addr_cached(rec.node_id)
            if addr is not None:
                try:
                    self._peer(addr).notify(method, task_id.hex(), count)
                except Exception as e:
                    errors.swallow("client.stream_notify", e)
            return
        if method != "stream_close":
            return
        # The producing task already completed (inflight record gone) but
        # its unconsumed elements still sit pinned in node stores; close
        # must reach every holder so they GC. Probe the FIRST UNCONSUMED
        # element (count+1 — consumed ones may already be freed); if the
        # stream was fully drained there is nothing to GC.
        try:
            elem = ObjectID.for_task_return(task_id, count + 1)
            locs = self._head.call("locate_object", elem.hex(),
                                   timeout=tuning.CONTROL_CALL_TIMEOUT_S)
            for loc in locs or ():  # rpc-loop-ok: stream ack to each holder of the element
                try:
                    self._peer(loc["address"]).notify(
                        method, task_id.hex(), count)
                except Exception as e:
                    errors.swallow("client.stream_close_holder", e)
        except Exception as e:
            errors.swallow("client.stream_close_locate", e)

    def stream_ack(self, task_id: TaskID, consumed: int) -> None:
        self._stream_notify("stream_ack", task_id, consumed)

    def stream_close(self, task_id: TaskID, consumed: int) -> None:
        self._stream_notify("stream_close", task_id, consumed)

    def cancel_task(self, task_id: TaskID) -> None:
        with self._lock:
            rec = self._inflight.get(task_id)
        if rec is None:
            return
        addr = self._node_addr(rec.node_id)
        if addr is not None:
            try:
                self._peer(addr).call("cancel_task", task_id.binary())
            except Exception as e:
                errors.swallow("client.cancel_task", e)

    # -- objects -----------------------------------------------------------

    def get_object(self, ref: ObjectRef,
                   timeout: Optional[float] = None) -> SerializedValue:
        # One span for the whole locate/fetch/poll loop: in a timeline,
        # "time spent waiting in raytpu.get" is the question being asked.
        with tracing.span("object.get") as attrs:
            if tracing.enabled():
                attrs["oid"] = ref.id.hex()
            return self._get_object_impl(ref, timeout)

    def _get_object_impl(self, ref: ObjectRef,
                         timeout: Optional[float] = None) -> SerializedValue:
        deadline = None if timeout is None else Deadline.after(timeout)
        delay = tuning.OBJECT_POLL_MIN_S
        empty_since: Optional[float] = None
        while True:
            sv = self.store.try_get(ref.id)
            if sv is not None:
                if ref.id in self._done_returns:
                    with self._lock:
                        self._done_returns.pop(ref.id, None)
                return sv
            # The bounce seam: a get() blocked here while the head is
            # SIGKILLed rides _head_call's reconnect — the restarted head
            # reloads its object-directory snapshot and nodes re-announce,
            # so the locate resumes instead of failing the driver.
            locs = self._head_call("locate_object", ref.id.hex())
            for loc in locs or ():
                if loc["address"] == self._serve_address:
                    continue
                # One dead replica holder must not cost every getter a
                # full fetch timeout per poll: the per-peer breaker
                # fails the source over to other copies instantly.
                src = breaker_for(loc["address"])
                try:
                    src.allow()
                except CircuitOpenError:
                    continue
                try:
                    from raytpu.cluster.transfer import fetch_object

                    # Streams chunk replies straight into the driver
                    # store's receive region — the object is never held
                    # as one heap blob on the way in.
                    got = fetch_object(self._peer(loc["address"]),
                                       ref.id.hex(), self.store)
                except (ConnectionLost, RpcTimeoutError, ConnectionError,
                        OSError):
                    src.record_failure()
                    continue
                except Exception:
                    src.record_success()  # peer answered; fetch just failed
                    continue
                src.record_success()
                if got:
                    sv = self.store.try_get(ref.id)
                    if sv is not None:
                        return sv
            if not locs:
                # No copy anywhere. If the creating task is not running
                # and we hold its lineage, re-execute it (reference:
                # ObjectRecoveryManager lineage reconstruction).
                now = time.monotonic()
                if empty_since is None:
                    empty_since = now
                elif now - empty_since > 0.5:
                    empty_since = now
                    with self._lock:
                        producing = None
                        for rec in self._inflight.values():
                            if ref.id in rec.spec.return_ids():
                                producing = rec.spec
                                break
                        dead = (self._dead_actors.get(producing.actor_id)
                                if producing is not None
                                and producing.actor_id is not None
                                else None)
                    if producing is None:
                        if not self._reconstruct(ref.id):
                            # No lineage (or its retry budget is spent).
                            # If the producer completed on a node that
                            # has since died, the value is unrecoverable.
                            self._fail_if_producer_gone(ref.id)
                    elif dead is not None:
                        # Stale-location race on actor death: the actor
                        # announced this result from its dying node
                        # (so _mark_actor_dead skipped the ref — it
                        # looked located), then the location purged with
                        # the node. An actor-call return has no lineage;
                        # nothing will ever reproduce it. Fail the ref
                        # or this getter waits forever.
                        self._fail_refs(producing, ActorDiedError(
                            producing.actor_id.hex(), dead))
            else:
                empty_since = None
            if deadline is not None and deadline.expired:
                raise GetTimeoutError(
                    f"object {ref.id.hex()} not ready within {timeout}s")
            time.sleep(delay)
            delay = min(delay * 2, tuning.OBJECT_POLL_MAX_S)

    def object_ready(self, ref: ObjectRef) -> bool:
        if self.store.contains(ref.id):
            return True
        try:
            return bool(self._head.call("locate_object", ref.id.hex()))
        except Exception:
            return False

    def wait_any_object_ready(self, refs, timeout=None):
        """Event-driven readiness for stream consumers (VERDICT r3 weak
        #5): the head pushes ``object::<id>`` the moment the first copy
        is reported, so no poll round-trips happen while waiting.
        Returns True when some ref is ready, False on timeout, None when
        this backend can't wait event-driven (relay mode — per-element
        proxy subscriptions would accumulate; callers fall back to
        polling)."""
        if self._relay is not None:
            return None
        if any(self.store.contains(r.id) for r in refs):
            return True
        ev = threading.Event()
        topics = [f"object::{r.id.hex()}" for r in refs]

        def _on_push(_d):
            ev.set()

        for t in topics:
            self._head.subscribe(t, _on_push)
        try:
            ready = False
            for r in refs:  # rpc-loop-ok: one readiness scan at wait() entry
                try:
                    if self._head.call("locate_object", r.id.hex(), True):
                        ready = True
                except Exception:
                    return None  # head unreachable: let the caller poll
            if ready or any(self.store.contains(r.id) for r in refs):
                return True
            return ev.wait(timeout if timeout is not None else 5.0)
        finally:
            for t in topics:
                self._head.unsubscribe(t, _on_push)

    # -- failure handling --------------------------------------------------

    def _fail_refs(self, spec: TaskSpec, err: BaseException) -> None:
        sv = serialize(err)
        for oid in spec.return_ids():
            self.store.put(oid, sv)
        if task_events.enabled():
            task_events.emit("task", spec.task_id.hex(),
                             task_events.TaskTransition.FAILED,
                             name=spec.name, attempt=spec.attempt,
                             error=f"{type(err).__name__}: {err}")
            log_dir = getattr(self._node, "log_dir", None)
            if log_dir:
                task_events.write_postmortem(
                    log_dir, f"task {spec.name} failed terminally "
                    f"(attempt {spec.attempt}): {type(err).__name__}")

    def _record_done_return(self, spec: TaskSpec, node_id: str) -> None:
        """Caller holds self._lock. Remember where a finished task left
        its still-unfetched returns so their loss is attributable later."""
        for oid in spec.return_ids():
            if self.store.contains(oid):
                continue
            self._done_returns[oid] = (spec.actor_id, node_id)
            self._done_returns.move_to_end(oid)
        while len(self._done_returns) > tuning.DONE_RETURN_MEMORY:
            self._done_returns.popitem(last=False)

    def _fail_if_producer_gone(self, oid: ObjectID) -> bool:
        """Called when an object has no copy anywhere, no in-flight
        producer, and no lineage. If its producing task is known to have
        completed on a node that is no longer alive, the value died with
        the node and nothing will ever reproduce it (actor returns carry
        no lineage) — fail the ref so blocked getters raise instead of
        polling forever."""
        with self._lock:
            entry = self._done_returns.get(oid)
        if entry is None:
            return False
        actor_id, node_id = entry
        if node_id and self._node_addr(node_id) is not None:
            # Producer's node is alive: the empty directory is a
            # transient miss (e.g. head mid-reload), not a loss.
            return False
        if actor_id is not None:
            with self._lock:
                reason = self._dead_actors.get(actor_id, "its node died")
            err: BaseException = ActorDiedError(
                actor_id.hex(),
                f"call completed but its result was lost with the "
                f"node ({reason})")
        else:
            err = ObjectLostError(
                f"object {oid.hex()} completed on node "
                f"{(node_id or '?')[:12]}, which died before any copy "
                f"was fetched")
        self.store.put(oid, serialize(err))
        with self._lock:
            self._done_returns.pop(oid, None)
        return True

    def _on_node_event(self, data: dict) -> None:
        if data.get("event") != "removed":
            return
        node_id = data["node_id"]
        with self._lock:
            self._addr_cache.pop(node_id, None)
            doomed = [rec for rec in self._inflight.values()
                      if rec.node_id == node_id]
            for rec in doomed:
                self._inflight.pop(rec.spec.task_id, None)
            dead_actor_ids = [aid for aid, nid in self._actor_nodes.items()
                              if nid == node_id]
        for rec in doomed:
            spec = rec.spec
            done = all(
                self.store.contains(oid) or
                self._safe_located(oid)
                for oid in spec.return_ids()
            )
            if done:
                continue
            if spec.attempt < spec.max_retries:
                spec.attempt += 1
                if task_events.enabled():
                    task_events.emit(
                        "task", spec.task_id.hex(),
                        task_events.TaskTransition.RETRIED,
                        name=spec.name, attempt=spec.attempt,
                        error=f"node {node_id[:12]} died")
                try:
                    self._route_task(spec)
                except Exception as e:
                    self._fail_refs(spec, e)
            else:
                self._fail_refs(spec, WorkerCrashedError(
                    f"node {node_id[:12]} died running task "
                    f"{spec.name} (attempt {spec.attempt})"))
        for aid in dead_actor_ids:
            self._mark_actor_dead(aid, f"node {node_id[:12]} died")

    def _safe_located(self, oid: ObjectID) -> bool:
        try:
            return bool(self._head_call(
                "locate_object", oid.hex(),
                timeout=tuning.CONTROL_CALL_TIMEOUT_S))
        except Exception:
            return False

    def _on_log_event(self, data: dict) -> None:
        """Worker output streamed to the driver terminal (reference:
        ``log_to_driver``; prefix identifies the producing process)."""
        import sys as _sys

        src = data.get("source", "?")
        nid = str(data.get("node_id", ""))[:8]
        for line in data.get("lines", ()):
            print(f"({src}, node={nid}) {line}", file=_sys.stderr)

    def _on_task_event(self, data: dict) -> None:
        """Explicit completion from the executing node: release the
        submitted-arg pins now — return-object locations are not a
        reliable completion signal (a fire-and-forget return may already
        be freed). The node_id match keeps a late event from a dead
        node's attempt from unpinning a resubmitted task."""
        if data.get("event") != "done":
            return
        try:
            tid = TaskID.from_hex(data["task_id"])
        except Exception:
            return
        with self._lock:
            rec = self._inflight.get(tid)
            # Empty node_id = head-queued spec (the head picked the node;
            # this driver never knew it) — any node's done event counts.
            if rec is None or (data.get("node_id") and rec.node_id
                               and rec.node_id != data["node_id"]):
                return
            self._inflight.pop(tid, None)
            if rec.spec.actor_id is not None:
                lst = self._actor_inflight.get(rec.spec.actor_id)
                if lst and rec.spec in lst:
                    lst.remove(rec.spec)
            self._record_done_return(
                rec.spec, data.get("node_id") or rec.node_id)
        self._unpin_args(rec.spec)

    def _on_object_event(self, data: dict) -> None:
        """A node reported an object with zero copies (its producer's node
        died after completion): reconstruct from lineage if we own it."""
        if data.get("event") != "unavailable":
            return
        try:
            oid = ObjectID.from_hex(data["object_id"])
        except Exception:
            return
        if not self.store.contains(oid):
            if not self._reconstruct(oid):
                self._fail_if_producer_gone(oid)

    def _on_actor_event(self, data: dict) -> None:
        event = data.get("event")
        aid_hex = data.get("actor_id")
        if not aid_hex:
            return
        actor_id = ActorID.from_hex(aid_hex)
        if event == "dead":
            self._mark_actor_dead(actor_id, data.get("reason", "actor died"))
        elif event == "restarting":
            # Head is restarting it: drop the stale location and fail only
            # the tasks that were in flight on the dead incarnation; new
            # submissions wait for the restart (reference: clients buffer
            # while GCS restarts the actor).
            with self._lock:
                self._actor_nodes.pop(actor_id, None)
                pending = self._actor_inflight.pop(actor_id, [])
            err = ActorDiedError(
                actor_id.hex(),
                f"actor restarting: {data.get('reason', '')} (in-flight "
                f"calls on the dead incarnation are lost)")
            for spec in pending:
                if not any(self._safe_located(oid)
                           for oid in spec.return_ids()):
                    self._fail_refs(spec, err)
        elif event == "restarted":
            with self._lock:
                self._actor_nodes[actor_id] = data.get("node_id")
                self._dead_actors.pop(actor_id, None)

    def _mark_actor_dead(self, actor_id: ActorID, reason: str) -> None:
        with self._lock:
            self._dead_actors[actor_id] = reason
            self._actor_nodes.pop(actor_id, None)
            pending = self._actor_inflight.pop(actor_id, [])
        err = ActorDiedError(actor_id.hex(), reason)
        for spec in pending:
            if not all(self.store.contains(oid)
                       for oid in spec.return_ids()):
                # The executing node may have stored results before dying;
                # only fail refs that will never materialize.
                if not any(self._safe_located(oid)
                           for oid in spec.return_ids()):
                    self._fail_refs(spec, err)

    # -- placement groups --------------------------------------------------

    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str,
                               name: str = "") -> PlacementGroupID:
        pg_id = PlacementGroupID.from_random()
        # The head's availability view lags heartbeats (and is optimistically
        # debited by recent schedules), so transient infeasibility is normal;
        # PGs are pending-until-placeable (reference: GCS PG state machine).
        deadline = Deadline.after(tuning.PG_CREATE_TIMEOUT_S)
        while True:
            try:
                result = self._head_call("create_pg", pg_id.hex(), bundles,
                                         strategy)
                break
            except PlacementInfeasibleError:
                if deadline.expired:
                    raise
                time.sleep(tuning.PG_POLL_PERIOD_S)
        placement: List[str] = result["nodes"]
        # Tell each node to reserve its shard under this pg id.
        by_node: Dict[str, List[Tuple[int, Dict[str, float]]]] = {}
        for idx, node_id in enumerate(placement):
            by_node.setdefault(node_id, []).append((idx, bundles[idx]))
        try:
            for node_id, indexed in by_node.items():  # rpc-loop-ok: one shard RPC per PG node by design
                addr = self._node_addr(node_id)
                if addr is None:
                    raise PlacementGroupError(
                        f"node {node_id[:12]} vanished during pg creation")
                self._peer(addr).call(
                    "create_pg_shard", pg_id.binary(), indexed, strategy,
                    len(bundles))
        except Exception:
            self._head_call("remove_pg", pg_id.hex())
            raise
        with self._lock:
            self._pgs[pg_id] = {"nodes": placement, "bundles": bundles,
                                "strategy": strategy, "state": "created"}
        return pg_id

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
        info = pg or self._head_call("pg_info", pg_id.hex())
        if info is None:
            return
        for node_id in set(info["nodes"]):  # rpc-loop-ok: PG teardown fan-out, cold path
            if node_id is None:
                continue
            addr = self._node_addr(node_id)
            if addr is not None:
                try:
                    self._peer(addr).call("remove_pg_shard", pg_id.binary())
                except Exception as e:
                    errors.swallow("client.remove_pg_shard", e)
        self._head_call("remove_pg", pg_id.hex())

    def placement_group_info(self, pg_id: PlacementGroupID) -> Optional[dict]:
        with self._lock:
            pg = self._pgs.get(pg_id)
        if pg is None:
            info = self._head_call("pg_info", pg_id.hex())
            if info is None:
                return None
            pg = info | {"state": "created"}
        return {
            "id": pg_id.hex(),
            "state": pg["state"],
            "strategy": pg["strategy"],
            "bundles": list(pg["bundles"]),
            "nodes": list(pg["nodes"]),
            "chip_coords": [[] for _ in pg["bundles"]],
        }

    # -- blocked workers (driver never executes tasks) ---------------------

    def task_blocked(self, task_id: TaskID) -> None:
        pass

    def task_unblocked(self, task_id: TaskID) -> None:
        pass

    # -- introspection -----------------------------------------------------

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self._head_call("list_nodes"):
            if n["alive"] and n["labels"].get("role") != "driver":
                for k, v in n["available"].items():
                    out[k] = out.get(k, 0.0) + v
        return out

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self._head_call("list_nodes"):
            if n["alive"] and n["labels"].get("role") != "driver":
                for k, v in n["resources"].items():
                    out[k] = out.get(k, 0.0) + v
        return out

    def nodes(self) -> List[dict]:
        return [
            {
                "NodeID": n["node_id"],
                "Alive": n["alive"],
                "Resources": n["resources"],
                "Available": n["available"],
                "Address": n["address"],
                "Labels": n["labels"],
            }
            for n in self._head_call("list_nodes")
        ]

    def task_events(self) -> List[dict]:
        return list(self._driver_backend.task_events())

    def trace_dump(self) -> List[dict]:
        """Every cluster process's span ring buffer, via the head's
        fan-out (head → nodes → workers). The driver's own buffer is NOT
        in here — :func:`raytpu.util.tracing.cluster_timeline` appends
        it locally."""
        out = self._head.call("trace_dump", "cluster")
        return out if isinstance(out, list) else []

    # -- kv (used by job submission / function shipping) -------------------

    def kv_put(self, key: str, value: bytes, overwrite: bool = True) -> bool:
        return self._head_call("kv_put", key, value, overwrite)

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._head_call("kv_get", key)

    def kv_del(self, key: str) -> bool:
        return self._head_call("kv_del", key)

    def shutdown(self) -> None:
        self._shutdown_flag = True
        # Non-detached actors die with their driver (reference: actors are
        # owned by the creating job unless lifetime="detached").
        with self._lock:
            own = [aid for aid, detached in self._my_actors.items()
                   if not detached and aid not in self._dead_actors]
        for aid in own:
            try:
                self.kill_actor(aid, no_restart=True)
            except Exception:
                pass
        if self._submit_queue is not None:
            # Sentinel rides behind any queued specs, so the submitter
            # flushes the window before exiting.
            try:
                self._submit_queue.put_nowait(None)
            except Exception:
                pass
            if self._submit_thread is not None:
                self._submit_thread.join(
                    timeout=tuning.SERVER_STOP_TIMEOUT_S)
        self._free_queue.put(None)
        # Final metrics flush: the driver's pending delta frames would
        # otherwise die with the embedded node's heartbeat loop. Pushed
        # straight to the head (one flag check when shipping is off).
        if _metrics.enabled():
            try:
                _metrics.collect(force=True)
                frames, dropped = _metrics.drain()
                if frames or dropped:
                    self._head.call(
                        "metrics_push", frames, dropped,
                        timeout=tuning.CONTROL_CALL_TIMEOUT_S)
            except Exception as e:
                errors.swallow("client.metrics_final_flush", e)
        # Same terminal flush for continuous-profile frames.
        if _profiler.profiling_enabled():
            try:
                frames, dropped = _profiler.prof_drain()
                if frames or dropped:
                    self._head.call(
                        "profile_push", frames, dropped,
                        timeout=tuning.CONTROL_CALL_TIMEOUT_S)
            except Exception as e:
                errors.swallow("client.profile_final_flush", e)
        try:
            if self._node is not None:
                self._node.stop()
            else:
                self._driver_backend.shutdown()
        except Exception:
            pass
        try:
            self._head.close()
        except Exception:
            pass
        with self._peers_lock:
            for c in self._peers.values():
                c.close()
            self._peers.clear()
        if self._relay is not None:
            try:
                self._relay.close()
            except Exception:
                pass
