"""Cluster-layer timing knobs: every timeout, poll period, and backoff
parameter the cluster layer uses, in one place, env-overridable.

Reference analogue: Ray's ``RAY_*`` timing env vars
(``ray_config_def.h`` — e.g. ``RAY_health_check_timeout_ms``,
``RAY_grpc_client_keepalive_timeout_ms``). PR 1 started the pattern for
heartbeats (``RAYTPU_HEARTBEAT_TIMEOUT_S`` in ``head.py``); this module
finishes it — a numeric ``timeout=`` literal or bare ``time.sleep(0.5)``
in ``raytpu/cluster/`` is now a lint failure (see
``tests/test_resilience.py::TestNoHardcodedTimeouts``), because scattered
magic timeouts are how one slow peer becomes an undebuggable gray
failure: nobody can say which knob to turn, and no two sites agree.

Naming: ``RAYTPU_<CONSTANT_NAME>`` env var overrides each value. Periods
end in ``_PERIOD_S``, budgets in ``_TIMEOUT_S``, backoff bounds in
``_DELAY_S``.
"""

from __future__ import annotations

import os


def _f(name: str, default: float) -> float:
    return float(os.environ.get(f"RAYTPU_{name}", str(default)))


def _i(name: str, default: int) -> int:
    return int(os.environ.get(f"RAYTPU_{name}", str(default)))


def _s(name: str, default: str) -> str:
    return os.environ.get(f"RAYTPU_{name}", default)


# -- RPC substrate -----------------------------------------------------------

# Default reply budget for RpcClient.call when the caller passes none.
RPC_CALL_TIMEOUT_S = _f("RPC_CALL_TIMEOUT_S", 30.0)
# TCP connect budget for a new RpcClient / RelayChannel.
RPC_CONNECT_TIMEOUT_S = _f("RPC_CONNECT_TIMEOUT_S", 10.0)
# RpcServer.start() waits this long for the loop thread to bind.
SERVER_START_TIMEOUT_S = _f("SERVER_START_TIMEOUT_S", 10.0)
# RpcServer.stop() waits this long for the loop thread to exit.
SERVER_STOP_TIMEOUT_S = _f("SERVER_STOP_TIMEOUT_S", 5.0)

# -- RPC batching (control-plane fast path) ----------------------------------

# Master switch for the batched fast path: wire-frame coalescing in
# RpcClient/Peer plus the driver's pipelined submit_batch window. Off by
# default — batch-off stays byte-compatible with the pre-batch wire.
RPC_BATCH = _i("RPC_BATCH", 0) != 0
# A coalescing flush stops growing at this many sub-frames ...
RPC_BATCH_MAX_FRAMES = _i("RPC_BATCH_MAX_FRAMES", 128)
# ... or this many coalesced payload bytes, whichever comes first.
RPC_BATCH_MAX_BYTES = _i("RPC_BATCH_MAX_BYTES", 1 << 20)
# Extra time a non-empty flush may wait for stragglers. 0 = pure
# group-commit: flush immediately when the link is idle, coalesce only
# what queued while the previous write was in flight.
RPC_BATCH_MAX_WAIT_S = _f("RPC_BATCH_MAX_WAIT_S", 0.0)
# Driver-side pipelined submission: bounded in-flight window (specs
# queued but not yet shipped; enqueue blocks beyond this) and the max
# specs the submitter coalesces into one head submit_batch RPC.
SUBMIT_WINDOW = _i("SUBMIT_WINDOW", 1024)
SUBMIT_BATCH_MAX = _i("SUBMIT_BATCH_MAX", 256)

# -- locality-aware scheduling -----------------------------------------------

# Master switch: among feasible nodes, prefer the one already holding
# the most argument bytes before applying the pack/spread policy.
# Advisory only — with LOCALITY=0 placement decisions are byte-identical
# to the plain pack/spread scheduler.
LOCALITY = _i("LOCALITY", 1) != 0
# Local-bytes totals below this never steer a placement: shipping a few
# KiB is cheaper than packing against the utilization policy.
LOCALITY_MIN_BYTES = _i("LOCALITY_MIN_BYTES", 64 * 1024)
# Bound on the head's oid -> size map feeding the locality scorer;
# beyond it the oldest sizes are evicted (the scorer merely loses
# signal for them — locations and correctness are unaffected).
LOCALITY_DIR_MAX = _i("LOCALITY_DIR_MAX", 100_000)
# When locality loses (resources force a remote placement), the head
# asks a holder to eagerly push args >= LOCALITY_MIN_BYTES to the
# chosen node so the transfer overlaps queueing. 0 disables.
LOCALITY_EAGER_PUSH = _i("LOCALITY_EAGER_PUSH", 1) != 0
# Node-side bound on buffered object-location deltas ("+"/"-" per oid)
# awaiting a coalesced report_objects flush or heartbeat piggyback.
OBJ_REPORT_BUFFER_MAX = _i("OBJ_REPORT_BUFFER_MAX", 8192)

# -- multi-tenant scheduling -------------------------------------------------

# Master switch for tenant-aware scheduling (quotas, weighted fair
# queueing, priority preemption, admission shedding). Off by default —
# with TENANTS=0 placement decisions are decision-identical to the
# tenant-blind scheduler (the RAYTPU_LOCALITY=0 contract).
TENANTS = _i("TENANTS", 0) != 0
# Stride weight for a tenant with no explicit row (higher = larger
# fair share of the pending-queue replay).
TENANT_DEFAULT_WEIGHT = _f("TENANT_DEFAULT_WEIGHT", 1.0)
# Static quota bootstrap parsed at head start, merged under any rows
# already persisted in the tenants table. Grammar:
#   "tenantA=CPU:4,TPU:8;tenantB=CPU:2"  (resource ceilings per tenant)
TENANT_QUOTAS = _s("TENANT_QUOTAS", "")
# Admission control: a tenant with this many queued (pending/infeasible)
# specs has further submissions shed with TenantThrottled instead of
# growing the head's queues unboundedly.
TENANT_MAX_QUEUED = _i("TENANT_MAX_QUEUED", 1024)
# retry_after hint carried on TenantThrottled; the client's RetryPolicy
# sleeps at least this long before re-submitting.
TENANT_RETRY_DELAY_S = _f("TENANT_RETRY_DELAY_S", 0.5)
# Priority preemption (within TENANTS): a starved higher-priority
# tenant may cancel the lowest-priority preemptible running task of an
# over-quota tenant (lineage re-executes it later).
TENANT_PREEMPT = _i("TENANT_PREEMPT", 1) != 0
# Preemptions issued per pending-queue scan — bounds preemption storms
# to the scan cadence (HEAD_PENDING_SCHED_PERIOD_S).
TENANT_PREEMPT_MAX_PER_SCAN = _i("TENANT_PREEMPT_MAX_PER_SCAN", 1)

# -- control-plane calls -----------------------------------------------------

# Small metadata RPCs (heartbeat, register, locate, free, failpoint
# arming): short budget — if one of these is slow the peer is sick.
CONTROL_CALL_TIMEOUT_S = _f("CONTROL_CALL_TIMEOUT_S", 5.0)
# locate_object from a node resolving a task argument.
LOCATE_TIMEOUT_S = _f("LOCATE_TIMEOUT_S", 10.0)
# Node drain (graceful stop) per-node budget.
DRAIN_TIMEOUT_S = _f("DRAIN_TIMEOUT_S", 2.0)

# -- data plane --------------------------------------------------------------

# Whole-object chunked transfer budget (fetch_blob / push_blob).
FETCH_TIMEOUT_S = _f("FETCH_TIMEOUT_S", 60.0)
# Object fetch from inside a worker process (smaller objects, hotter path).
WORKER_FETCH_TIMEOUT_S = _f("WORKER_FETCH_TIMEOUT_S", 30.0)
# Cap on one blocking wait_objects_any poll (server-side hold).
WAIT_POLL_CAP_S = _f("WAIT_POLL_CAP_S", 300.0)
# Process-wide in-flight transfer payload budget in BYTES, shared by push
# and pull (replaces the count-only chunk semaphore: N chunks ballooned
# with the chunk-size knob; a bytes window is invariant to it).
TRANSFER_WINDOW_BYTES = _i("TRANSFER_WINDOW_BYTES", 64 * 1024 * 1024)
# Sender-side chunk-serving RangeReader cache TTL: the reader (and the
# store pin backing it) lives this long past the last chunk request.
TX_READER_TTL_S = _f("TX_READER_TTL_S", 30.0)

# -- actors / placement ------------------------------------------------------

# Budget for resolving an actor's node (restart in flight).
ACTOR_RESOLVE_TIMEOUT_S = _f("ACTOR_RESOLVE_TIMEOUT_S", 30.0)
# create_actor RPC (spawns a worker: slow path).
CREATE_ACTOR_TIMEOUT_S = _f("CREATE_ACTOR_TIMEOUT_S", 120.0)
# Placement-group creation end-to-end budget.
PG_CREATE_TIMEOUT_S = _f("PG_CREATE_TIMEOUT_S", 15.0)

# -- workers -----------------------------------------------------------------

# WorkerPool.lease: budget for a free worker to appear.
WORKER_LEASE_TIMEOUT_S = _f("WORKER_LEASE_TIMEOUT_S", 300.0)
# Graceful worker shutdown before SIGKILL.
WORKER_KILL_TIMEOUT_S = _f("WORKER_KILL_TIMEOUT_S", 2.0)

# -- poll periods ------------------------------------------------------------

# Driver-side pending-task scan.
PENDING_POLL_PERIOD_S = _f("PENDING_POLL_PERIOD_S", 0.2)
# Actor-restart wait poll (driver and node routing).
RESTART_POLL_PERIOD_S = _f("RESTART_POLL_PERIOD_S", 0.1)
# Placement-group readiness poll.
PG_POLL_PERIOD_S = _f("PG_POLL_PERIOD_S", 0.25)
# Worker-pool monitor thread scan.
MONITOR_POLL_PERIOD_S = _f("MONITOR_POLL_PERIOD_S", 0.05)
# Object-arrival poll floor/ceiling for driver get_object.
OBJECT_POLL_MIN_S = _f("OBJECT_POLL_MIN_S", 0.005)
OBJECT_POLL_MAX_S = _f("OBJECT_POLL_MAX_S", 0.1)
# Node-side wait for an already-inbound push to land before pulling.
PUSH_WAIT_POLL_PERIOD_S = _f("PUSH_WAIT_POLL_PERIOD_S", 0.02)
# Metric snapshot/ship cadence: every process folds its registry deltas
# into a pending frame at most this often (frames then ride the next
# heartbeat / worker notify, so the effective ship period is
# max(this, the carrier's period)).
METRICS_SHIP_PERIOD_S = _f("METRICS_SHIP_PERIOD_S", 2.0)

# -- durable head / elastic cluster ------------------------------------------

# Head-side write-behind snapshot cadence for the derived tables (object
# directory, borrow sets, flight-recorder tail) — per-mutation rows are
# too hot for those; everything else persists write-after-mutation.
HEAD_SNAPSHOT_PERIOD_S = _f("HEAD_SNAPSHOT_PERIOD_S", 10.0)
# Head-side queued-infeasible TaskSpec re-schedule scan.
HEAD_PENDING_SCHED_PERIOD_S = _f("HEAD_PENDING_SCHED_PERIOD_S", 0.2)
# Driver-side budget to re-dial a bounced head before an in-flight
# get()/schedule() fails with WorkerCrashedError.
HEAD_RECONNECT_TIMEOUT_S = _f("HEAD_RECONNECT_TIMEOUT_S", 30.0)
# A pending (infeasible) placement group feeds autoscaler demand for
# this long past its last create attempt; the client retry loop
# refreshes the entry while the caller still wants the PG.
PG_DEMAND_TTL_S = _f("PG_DEMAND_TTL_S", 30.0)
# Elastic gang training: budget for the post-failure capacity probe
# (how long fit() waits for ANY feasible world size >= min_workers),
# the probe's poll period, and how often a running gang checks whether
# replacement capacity arrived so it can scale back up at the next
# checkpoint boundary.
ELASTIC_PROBE_TIMEOUT_S = _f("ELASTIC_PROBE_TIMEOUT_S", 30.0)
ELASTIC_PROBE_PERIOD_S = _f("ELASTIC_PROBE_PERIOD_S", 0.5)
ELASTIC_UPSCALE_CHECK_PERIOD_S = _f("ELASTIC_UPSCALE_CHECK_PERIOD_S", 2.0)
# Driver-side memory of completed-but-unfetched return objects (oid ->
# producing actor/node). Consulted when a get() finds no copy anywhere:
# if the producer finished on a node that then died, the value is gone
# for good (actor returns carry no lineage) and the ref is failed
# instead of polled forever. FIFO-bounded; eviction only narrows the
# hang protection for very old refs.
DONE_RETURN_MEMORY = _i("DONE_RETURN_MEMORY", 4096)

# -- hot-standby head (WAL shipping, lease election, fencing) ----------------

# Lease TTL: the active head must renew its epoch-stamped lease within
# this window or the standby elects itself. The same value bounds how
# long a SIGSTOP'd incumbent may be paused before it must assume it has
# been superseded (it re-reads the discovery record and self-fences).
HEAD_LEASE_TTL_S = _f("HEAD_LEASE_TTL_S", 3.0)
# How often the active head rewrites the lease row (must be well under
# the TTL so one missed renewal doesn't trigger an election).
HEAD_LEASE_RENEW_PERIOD_S = _f("HEAD_LEASE_RENEW_PERIOD_S", 1.0)
# Follower poll cadence for the wal_ship RPC. Each successful poll both
# replicates new WAL entries and proves the incumbent holds its lease.
WAL_SHIP_PERIOD_S = _f("WAL_SHIP_PERIOD_S", 0.1)
# Bounded per-table in-memory WAL journal on the head. A follower whose
# cursor fell behind the journal horizon gets a full-table resync
# instead of deltas — correct either way, this only sizes the window.
WAL_JOURNAL_MAX = _i("WAL_JOURNAL_MAX", 4096)
# Discovery record: a JSON file {"address", "epoch"} rewritten by
# whichever process currently serves as head. Clients and nodes re-read
# it on reconnect so failover needs no address reconfiguration. Empty
# string disables file-based discovery (redirect RPCs still work).
HEAD_ADDR_FILE = _s("HEAD_ADDR_FILE", "")
# Follower backoff after a failed wal_ship poll before redialing the
# incumbent (keeps a dead-head poll loop from spinning).
STANDBY_RECONNECT_DELAY_S = _f("STANDBY_RECONNECT_DELAY_S", 0.2)

# -- node → head reconnect ---------------------------------------------------

# Exponential backoff bounds for a node whose head is unreachable
# (replaces the tight reconnect-every-heartbeat loop).
RECONNECT_BASE_DELAY_S = _f("RECONNECT_BASE_DELAY_S", 0.2)
RECONNECT_MAX_DELAY_S = _f("RECONNECT_MAX_DELAY_S", 5.0)
# While the head is unreachable, a node buffers at most this many
# control-plane notifications (object/actor announcements) to replay
# after re-registering; older entries are dropped oldest-first.
HEAD_NOTIFY_BUFFER_MAX = _i("HEAD_NOTIFY_BUFFER_MAX", 1024)

# -- serving plane: router probes, prefix routing, KV handoff ----------------

# Queue-length / prefix-summary probe budget for the serve router. A
# replica that can't answer within this is scored worst-queue for the
# pick — NEVER assumed idle (a wedged replica that looked like a
# zero-length queue would attract every request).
SERVE_PROBE_TIMEOUT_S = _f("SERVE_PROBE_TIMEOUT_S", 2.0)
# Prefix-cache-aware routing master switch (0 = blind power-of-two
# choices, decision-identical to the pre-r19 router). Read at call
# time so tests can flip it without re-importing the router.
PREFIX_ROUTING = _i("PREFIX_ROUTING", 0)
# How long a router may reuse a replica's prefix-summary probe before
# re-fetching it. Longer = cheaper routing, staler match decisions.
PREFIX_SUMMARY_TTL_S = _f("PREFIX_SUMMARY_TTL_S", 1.0)
# Cap on digests per replica prefix summary (bounds probe payloads on
# replicas with huge caches; oldest registrations are dropped first).
PREFIX_SUMMARY_MAX = _i("PREFIX_SUMMARY_MAX", 1024)
# Upper age bound on a controller-pushed prefix summary before the
# router stops trusting it and falls back to a unicast probe. Pushed
# summaries ride health replies (one per health_check_period_s), so
# this must comfortably exceed that period; past it, a silent
# controller (partition, failover) degrades to per-replica probes
# instead of routing on a frozen view of the caches.
PREFIX_PUSH_MAX_AGE_S = _f("PREFIX_PUSH_MAX_AGE_S", 30.0)
# Chunk size for streaming KV pages between replicas during a
# disaggregated prefill→decode handoff. Each chunk is admitted through
# the process-wide transfer ByteWindow, so aggregate in-flight handoff
# bytes stay bounded alongside ordinary object transfers.
KV_STREAM_CHUNK_BYTES = _i("KV_STREAM_CHUNK_BYTES", 262144)
# How long a prefill replica keeps an opened-but-unfinished KV export
# pinned before assuming the decode peer died and freeing the pages.
KV_HANDOFF_TTL_S = _f("KV_HANDOFF_TTL_S", 30.0)
