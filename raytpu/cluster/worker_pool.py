"""Node-daemon worker pool: leases, reuse, chip isolation, crash reaping.

Reference analogue: ``src/ray/raylet/worker_pool.cc`` (1652 LoC) — idle
workers cached per (job, runtime-env) and popped per lease
(``worker_pool.h:343,354,417``); plus the TPU accelerator manager's
per-process chip isolation (``python/ray/_private/accelerators/tpu.py:
30-49``), which here happens at spawn: a worker bound to chips gets
``TPU_VISIBLE_CHIPS`` et al. in its environment and keeps that binding for
life (chip visibility can't change after the TPU runtime initializes).

Pool key: ``(job_id, runtime-env-hash, chips-tuple)``. A lease pops a
matching idle worker or spawns one; crashed workers are reaped by a
monitor thread which fails their in-flight work with
:class:`WorkerCrashedError` (the daemon survives — that is the point).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from raytpu.cluster import constants as tuning
from raytpu.cluster.protocol import RpcClient
from raytpu.core.config import cfg
from raytpu.util import errors
from raytpu.util import tracing
from raytpu.util.failpoints import DROP, failpoint
from raytpu.util.events import record_event
from raytpu.core.errors import WorkerCrashedError
from raytpu.core.ids import JobID, WorkerID


def runtime_env_hash(runtime_env: Optional[dict]) -> str:
    if not runtime_env:
        return ""
    try:
        return hashlib.sha1(
            json.dumps(runtime_env, sort_keys=True, default=str).encode()
        ).hexdigest()[:12]
    except Exception:
        return "unhashable"


def chip_env(chips: Tuple[int, ...]) -> Dict[str, str]:
    """Per-worker TPU visibility env (reference ``tpu.py:30-49``)."""
    if not chips:
        return {"RAYTPU_VISIBLE_CHIPS": ""}
    ids = ",".join(str(c) for c in chips)
    return {
        "RAYTPU_VISIBLE_CHIPS": ids,
        "TPU_VISIBLE_CHIPS": ids,
        "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,{len(chips)},1",
        "TPU_PROCESS_BOUNDS": "1,1,1",
    }


class WorkerHandle:
    def __init__(self, worker_id: WorkerID, key: tuple,
                 chips: Tuple[int, ...],
                 proc: Optional[subprocess.Popen] = None):
        self.worker_id = worker_id
        self.key = key
        self.chips = chips
        self.proc = proc  # None until _spawn (reserved slot)
        self.client: Optional[RpcClient] = None
        self.address: Optional[str] = None
        self.pid: Optional[int] = None
        self.ready = threading.Event()
        self.dead = False
        self.dedicated = False  # actor-bound: never returned to the pool
        self.kill_reason: Optional[str] = None  # set by pool.kill()
        # True while the worker's task sits in raytpu.get (blocked-worker
        # protocol): excluded from the pool soft cap so nested tasks can
        # always obtain a worker (reference: raylets exceed the soft limit
        # for blocked workers).
        self.blocked = False
        self.last_used = time.monotonic()
        self.on_death: Optional[Callable[[str], None]] = None  # actor hook
        # Container spec from the runtime env (the lease key pins the
        # image via the renv hash): _spawn wraps the worker command.
        self.container = None

    def crash(self, reason: str) -> None:
        self.dead = True
        if self.on_death is not None:
            try:
                self.on_death(reason)
            except Exception:
                pass
        if self.client is not None:
            self.client.close()


class WorkerPool:
    def __init__(self, node_address: str, shm_name: Optional[str],
                 node_id_hex: str, base_env: Optional[Dict[str, str]] = None,
                 soft_limit: Optional[int] = None,
                 log_dir: Optional[str] = None):
        self.node_address = node_address
        self.shm_name = shm_name or ""
        self.node_id_hex = node_id_hex
        self.log_dir = log_dir
        self.base_env = dict(base_env or {})
        # The cap must at least cover the CPU ledger, or tasks the
        # scheduler admitted would starve waiting for workers.
        self.soft_limit = max(int(cfg.num_workers_soft_limit) or 8,
                              int(soft_limit or 0))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._workers: Dict[str, WorkerHandle] = {}  # worker_id hex -> handle
        self._idle: Dict[tuple, List[WorkerHandle]] = {}
        self._stopped = False
        self.on_worker_gone = None  # cb(worker_id_hex); set by NodeServer
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="worker-pool-monitor", daemon=True)
        self._monitor.start()

    # -- registration (called from the node RPC handler) -------------------

    def on_register(self, worker_id_hex: str, address: str, pid: int) -> None:
        # drop => the registration is lost; the lease waiting on ready
        # times out exactly like a worker that wedged during startup.
        if failpoint("worker.register.pre") is DROP:
            return
        with self._lock:
            h = self._workers.get(worker_id_hex)
        if h is None:
            return
        h.address = address
        h.pid = pid
        try:
            h.client = RpcClient(address)
        except Exception:
            h.crash("worker RPC connect failed")
            return
        h.ready.set()

    # -- leasing -----------------------------------------------------------

    def lease(self, job_id: JobID, renv: Optional[dict],
              chips: Tuple[int, ...], *, dedicated: bool = False,
              timeout: Optional[float] = None) -> WorkerHandle:
        """Pop an idle matching worker or spawn one. Blocks on the soft
        process cap (reference: ``num_workers_soft_limit``)."""
        # The lease span separates "waiting for a worker" (cap waits,
        # cold spawns) from the task's own execution in a timeline.
        with tracing.span("worker.lease") as attrs:
            h = self._lease_impl(job_id, renv, chips, dedicated=dedicated,
                                 timeout=timeout)
            attrs["worker"] = h.worker_id.hex()[:12]
            return h

    def _lease_impl(self, job_id: JobID, renv: Optional[dict],
                    chips: Tuple[int, ...], *, dedicated: bool = False,
                    timeout: Optional[float] = None) -> WorkerHandle:
        failpoint("worker.lease.pre")
        key = (job_id.hex(), runtime_env_hash(renv), tuple(chips))
        if timeout is None:
            # Never wedge the dispatcher forever.
            timeout = tuning.WORKER_LEASE_TIMEOUT_S
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                if self._stopped:
                    raise WorkerCrashedError("pool stopped")
                idles = self._idle.get(key)
                while idles:
                    h = idles.pop()
                    if (not h.dead and h.proc is not None
                            and h.proc.poll() is None):
                        h.dedicated = dedicated
                        h.last_used = time.monotonic()
                        return h
                # Dedicated (actor) workers are bounded by the resource
                # ledger, not the pool cap, and blocked workers (sitting
                # in raytpu.get) are excluded so nested tasks can always
                # obtain a worker (reference: the soft limit only governs
                # idle/task workers; raylets exceed it for blocked ones).
                limit = self.soft_limit
                live = sum(1 for w in self._workers.values()
                           if not w.dead and not w.dedicated
                           and not w.blocked)
                if live >= limit:
                    # Over the cap: evict idle workers of other keys (e.g.
                    # finished jobs) to make room — LRU first. terminate()
                    # only sends a signal, so it is safe under the lock.
                    all_idle = sorted(
                        (h for hs in self._idle.values() for h in hs),
                        key=lambda h: h.last_used)
                    for victim in all_idle[:max(1, live - limit + 1)]:
                        self._drop_locked(victim)
                        victim.dead = True
                        try:
                            if victim.proc is not None:
                                victim.proc.terminate()
                        except Exception:
                            pass
                        live -= 1
                if live < limit or dedicated:
                    h = self._reserve_locked(key, chips)
                    h.dedicated = dedicated
                    if renv:
                        h.container = renv.get("container")
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise WorkerCrashedError(
                        "worker lease timed out at pool cap")
                self._cv.wait(timeout=min(remaining, 0.1))
        # Popen outside the lock: spawns overlap and never stall
        # lease/release/on_register traffic.
        try:
            self._spawn(h)
        except Exception as e:
            # e.g. container engine missing: fail the lease cleanly and
            # free the reserved slot instead of wedging on ready.wait.
            h.crash(f"worker spawn failed: {e}")
            with self._lock:
                self._workers.pop(h.worker_id.hex(), None)
            raise WorkerCrashedError(f"worker spawn failed: {e}") from e
        if not h.ready.wait(timeout=float(cfg.worker_register_timeout_seconds)):
            h.crash("worker failed to register in time")
            try:
                if h.proc is not None:
                    h.proc.terminate()  # never leak an orphan holding chips
            except Exception:
                pass
            with self._lock:
                self._workers.pop(h.worker_id.hex(), None)
            raise WorkerCrashedError("worker failed to start")
        if h.dead:
            raise WorkerCrashedError("worker died during startup")
        return h

    def release(self, h: WorkerHandle) -> None:
        """Return a leased worker to the idle cache (or drop it if dead)."""
        with self._lock:
            if (h.dead or h.dedicated or self._stopped
                    or h.client is None or h.client.closed
                    or h.proc is None or h.proc.poll() is not None):
                self._drop_locked(h)
            else:
                h.last_used = time.monotonic()
                self._idle.setdefault(h.key, []).append(h)
            self._cv.notify_all()

    def kill(self, h: WorkerHandle, reason: str = "killed",
             failure: bool = False) -> None:
        h.kill_reason = reason  # surfaced in the task's failure message
        # Already-dead workers were reported by the reaper (WORKER_CRASHED)
        # — a cleanup kill must not double-log the incident. Routine kills
        # (raytpu.kill, idle reaping) stay INFO; callers mark failures.
        if not h.dead:
            record_event("ERROR" if failure else "INFO", "WORKER_KILLED",
                         f"worker {h.worker_id.hex()[:8]} killed: {reason}",
                         worker_id=h.worker_id.hex(), reason=reason)
        try:
            if h.client is not None and not h.client.closed:
                h.client.call("kill", reason,
                              timeout=tuning.WORKER_KILL_TIMEOUT_S)
        except Exception as e:
            errors.swallow("pool.kill_rpc", e)
        try:
            if h.proc is not None:
                h.proc.terminate()
        except Exception:
            pass
        with self._lock:
            self._drop_locked(h)
            self._cv.notify_all()

    # -- internals ---------------------------------------------------------

    def _reserve_locked(self, key: tuple,
                        chips: Tuple[int, ...]) -> WorkerHandle:
        worker_id = WorkerID.from_random()
        h = WorkerHandle(worker_id, key, chips, proc=None)
        self._workers[worker_id.hex()] = h
        return h

    def _spawn(self, h: WorkerHandle) -> None:
        if h.proc is not None:
            return  # popped from idle, already running
        failpoint("worker.spawn.pre")
        # os.environ carries RAYTPU_FAILPOINTS, so failpoints armed with
        # env=True (or inherited by this daemon) reach the worker too.
        env = dict(os.environ)
        env.update(self.base_env)
        env.update(chip_env(h.chips))
        # The host this node is reachable at — gang rendezvous publishes
        # coordinator addresses on it (a worker cannot otherwise know its
        # externally visible IP).
        env.setdefault("RAYTPU_HOST_IP",
                       self.node_address.rsplit(":", 1)[0])
        cmd = [
            sys.executable, "-m", "raytpu.cluster.worker_proc",
            "--node", self.node_address,
            "--shm", self.shm_name,
            "--worker-id", h.worker_id.hex(),
            "--job", h.key[0],
            "--node-id", self.node_id_hex,
        ]
        # Container wrap BEFORE any fd is opened: a failed wrap (e.g. no
        # engine on the node) must not leak log file handles.
        if h.container is not None:
            from raytpu.runtime_env.container import wrap_worker_command

            cmd, env = wrap_worker_command(cmd, env, h.container)
        # Per-process log files (reference: worker-<id>-<pid>.out/.err
        # under the session dir); the node's log monitor tails .out/.err
        # and streams new lines to drivers.
        stdout = stderr = None
        if self.log_dir:
            wid = h.worker_id.hex()[:12]
            stdout = open(os.path.join(
                self.log_dir, f"worker-{wid}.out"), "ab", buffering=0)
            stderr = open(os.path.join(
                self.log_dir, f"worker-{wid}.err"), "ab", buffering=0)
        try:
            h.proc = subprocess.Popen(cmd, env=env,
                                      start_new_session=True,
                                      stdout=stdout, stderr=stderr)
        finally:
            if stdout is not None:
                stdout.close()
                stderr.close()

    def _drop_locked(self, h: WorkerHandle) -> None:
        self._workers.pop(h.worker_id.hex(), None)
        idles = self._idle.get(h.key)
        if idles and h in idles:
            idles.remove(h)
        # Borrow cleanup etc. — the callback must be cheap (it spawns its
        # own thread for any RPC work; we hold the pool lock here).
        if self.on_worker_gone is not None:
            try:
                self.on_worker_gone(h.worker_id.hex())
            except Exception:
                pass

    def _monitor_loop(self) -> None:
        while not self._stopped:
            time.sleep(tuning.MONITOR_POLL_PERIOD_S)
            dead: List[WorkerHandle] = []
            idle_kill: List[WorkerHandle] = []
            now = time.monotonic()
            idle_ttl = float(cfg.idle_worker_killing_time_threshold_ms) / 1e3
            with self._lock:
                for h in list(self._workers.values()):
                    if h.dead or h.proc is None:
                        continue
                    if h.proc.poll() is not None:
                        dead.append(h)
                        self._drop_locked(h)
                    elif (not h.dedicated and h.ready.is_set()
                          and now - h.last_used > idle_ttl
                          and any(h is w for w in
                                  self._idle.get(h.key, ()))):
                        idle_kill.append(h)
                if dead or idle_kill:
                    self._cv.notify_all()
            for h in dead:
                record_event("ERROR", "WORKER_CRASHED",
                             f"worker {h.worker_id.hex()[:8]} exited with "
                             f"code {h.proc.returncode}",
                             worker_id=h.worker_id.hex(),
                             exit_code=h.proc.returncode)
                h.crash(f"worker process exited with code "
                        f"{h.proc.returncode}")
            for h in idle_kill:
                self.kill(h, "idle timeout")

    def stats(self) -> dict:
        with self._lock:
            return {
                "workers": len(self._workers),
                "idle": sum(len(v) for v in self._idle.values()),
            }

    def shutdown(self) -> None:
        self._stopped = True
        with self._lock:
            workers = list(self._workers.values())
            self._workers.clear()
            self._idle.clear()
        for h in workers:
            try:
                if h.proc is not None:
                    h.proc.terminate()
            except Exception:
                pass
        for h in workers:
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=tuning.WORKER_KILL_TIMEOUT_S)
            except Exception:
                try:
                    h.proc.kill()
                except Exception as e:
                    errors.swallow("worker_pool.kill_escalation", e)
