"""Worker subprocess: one leased executor process on a cluster node.

Reference analogue: the worker process popped from the raylet's
``WorkerPool`` (``src/ray/raylet/worker_pool.h:343,354,417``) hosting a
CoreWorker. Crash containment is the point: a segfaulting user task (or a
JAX/TPU runtime abort) kills *this* process, and the node daemon survives,
fails the task with :class:`WorkerCrashedError` and retries elsewhere.

The worker is an RPC *server* (the daemon pushes ``execute`` /
``create_actor`` / ``actor_task`` — the analogue of ``PushTask`` after a
lease grant) and an RPC *client* back to its daemon (object fetch for
missing args, nested task submission, blocked-worker notifications).

TPU chip isolation: the daemon spawns the worker with
``TPU_VISIBLE_CHIPS`` / ``TPU_CHIPS_PER_PROCESS_BOUNDS`` (and the
platform-agnostic ``RAYTPU_VISIBLE_CHIPS``) already in its environment, so
JAX in this process only ever sees its leased chips — reference:
``python/ray/_private/accelerators/tpu.py:30-49``.

Object plane: the worker attaches to the node's shared-memory store, so
large args are read zero-copy and large results are visible to the daemon
the moment they are sealed; small results ride back in the RPC reply.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from raytpu.cluster import constants as tuning
from raytpu.cluster import wire

from raytpu.cluster.protocol import Peer, RpcClient, RpcServer
from raytpu.util import errors
from raytpu.util import metrics
from raytpu.util import profiler
from raytpu.util import task_events, tracing
from raytpu.util.failpoints import failpoint
from raytpu.core.errors import ActorDiedError, TaskError
from raytpu.core.ids import JobID, NodeID, ObjectID, TaskID
from raytpu.runtime.object_ref import ObjectRef
from raytpu.runtime.object_store import MemoryStore
from raytpu.runtime.serialization import SerializedValue, serialize
from raytpu.runtime.task_spec import TaskSpec
from raytpu.runtime.worker import Worker


class WorkerBackend:
    """The backend seen by user code *inside* a worker process.

    Nested ``raytpu.remote``/``get``/``put`` calls route through the node
    daemon (the reference routes nested submissions through the local
    raylet the same way). Implements the subset of the backend surface
    that :mod:`raytpu.runtime.api` consumes.
    """

    def __init__(self, host: "_WorkerHost"):
        self._host = host
        self.worker = host.worker
        self.store = host.store

    # -- submission (forwarded to the daemon) ------------------------------

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = [ObjectRef(oid, owner=self.worker.worker_id.binary())
                for oid in spec.return_ids()]
        self._host.node.call("submit_task", wire.dumps(spec))
        return refs

    def create_actor(self, spec: TaskSpec) -> None:
        self._host.node.call("create_actor", wire.dumps(spec))

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        refs = [ObjectRef(oid, owner=self.worker.worker_id.binary())
                for oid in spec.return_ids()]
        self._host.node.call("submit_actor_task", wire.dumps(spec))
        return refs

    def kill_actor(self, actor_id, no_restart: bool = True) -> None:
        self._host.node.call("kill_actor", actor_id.hex(), no_restart)

    def get_actor_handle_info(self, name: str, namespace: str):
        info = self._host.node.call("get_actor_info", name, namespace)
        if info is None:
            raise ValueError(f"no actor named {name!r} in {namespace!r}")
        actor_id_hex, spec_blob = info
        from raytpu.core.ids import ActorID

        return ActorID.from_hex(actor_id_hex), wire.loads(spec_blob)

    def cancel_task(self, task_id: TaskID) -> None:
        self._host.node.call("cancel_task", task_id.binary())

    def actor_handle_added(self, actor_id) -> None:
        pass  # cluster actors live until killed or their node dies

    def actor_handle_removed(self, actor_id) -> None:
        pass

    # -- data plane --------------------------------------------------------

    def get_object(self, ref: ObjectRef, timeout: Optional[float] = None):
        return self._host.get_serialized(ref.id, timeout=timeout)

    def object_ready(self, ref: ObjectRef) -> bool:
        if self.store.contains(ref.id):
            return True
        try:
            return bool(self._host.node.call(
                "has_object", ref.id.hex(),
                timeout=tuning.CONTROL_CALL_TIMEOUT_S))
        except Exception:
            return False

    def wait_any_object_ready(self, refs, timeout=None):
        """Event-driven stream readiness via the daemon's async
        wait_objects_any (resolved by its object-arrival hook / head
        push); returns None on RPC failure so callers fall back to
        polling."""
        if any(self.store.contains(r.id) for r in refs):
            return True
        server_side = 5.0 if timeout is None else max(0.0, min(
            float(timeout), 60.0))
        try:
            return bool(self._host.node.call(
                "wait_objects_any", [r.id.hex() for r in refs],
                server_side, timeout=server_side + 10.0))
        except Exception:
            return None

    # -- streaming (nested consumption inside a worker) --------------------

    def stream_ack(self, task_id: TaskID, consumed: int) -> None:
        try:
            self._host.node.notify("stream_ack", task_id.hex(), consumed)
        except Exception as e:
            errors.swallow("worker.stream_ack", e)

    def stream_close(self, task_id: TaskID, consumed: int) -> None:
        try:
            self._host.node.notify("stream_close", task_id.hex(), consumed)
        except Exception as e:
            errors.swallow("worker.stream_close", e)

    # -- blocked-worker protocol ------------------------------------------

    def task_blocked(self, task_id: TaskID) -> None:
        try:
            self._host.node.notify("task_blocked", task_id.binary())
        except Exception as e:
            errors.swallow("worker.task_blocked", e)

    def task_unblocked(self, task_id: TaskID) -> None:
        try:
            self._host.node.notify("task_unblocked", task_id.binary())
        except Exception as e:
            errors.swallow("worker.task_unblocked", e)

    # -- introspection -----------------------------------------------------

    def available_resources(self) -> Dict[str, float]:
        return self._host.node.call("available_resources")

    def cluster_resources(self) -> Dict[str, float]:
        return self._host.node.call("cluster_resources")

    def nodes(self) -> List[dict]:
        return self._host.node.call("nodes")

    def task_events(self) -> List[dict]:
        return []

    def shutdown(self) -> None:
        pass


def _dump_err(name: str, err: BaseException) -> bytes:
    try:
        return cloudpickle.dumps(err)
    except Exception:
        return cloudpickle.dumps(TaskError.from_exception(name, err))


class _WorkerHost:
    """Execution state of one worker process."""

    def __init__(self, node_address: str, shm_name: Optional[str],
                 job_id: JobID, node_id: NodeID, worker_id_hex: str):
        self.node = RpcClient(node_address)
        self.worker_id_hex = worker_id_hex
        shm = None
        if shm_name:
            try:
                from raytpu.runtime.shm_store import attach

                shm = attach(shm_name)
            except Exception:
                shm = None
        self.store = MemoryStore(shm=shm)
        self.worker = Worker(job_id, node_id, self.store)
        # Results the daemon pins; our local refcount must not free them.
        self.worker.pin_owned = True
        # Borrower protocol (reference: reference_count.h borrowers): refs
        # this worker reported as still-held at task completion. When the
        # last local handle drops, tell the daemon so the owner's deferred
        # free can fire. Releases are queued: the out-of-scope hook runs
        # under ReferenceCounter._lock, and a socket write there would
        # block every ObjectRef create/delete in the process.
        import queue as _q

        self._borrowed: set = set()
        # Guards _borrowed so the hook's check+discard+enqueue and
        # collect_borrows' add/retract are atomic — without it both sides
        # can win the discard, the release fires for a borrow that was
        # never reported, and the head tombstones the NEXT legitimate
        # borrow for the pair (ADVICE r3). Leaf lock: never take
        # ReferenceCounter._lock while holding it (the hook already runs
        # under rc._lock, so the nesting order is rc -> borrow only).
        self._borrow_lock = threading.Lock()
        self._release_queue: "_q.Queue" = _q.Queue()
        prev_oos = self.worker.reference_counter._on_out_of_scope

        def _oos(oid):
            if prev_oos is not None:
                prev_oos(oid)
            with self._borrow_lock:
                queued = oid in self._borrowed
                if queued:
                    self._borrowed.discard(oid)
            if queued:
                self._release_queue.put(oid)

        self.worker.reference_counter._on_out_of_scope = _oos

        def _release_loop():
            while True:
                oid = self._release_queue.get()
                if oid is None:
                    return
                try:
                    self.node.notify("borrow_released", oid.hex(),
                                     self.worker_id_hex)
                except Exception as e:
                    errors.swallow("worker.borrow_released", e)

        threading.Thread(target=_release_loop, name="borrow-release",
                         daemon=True).start()
        self.actor_instance: Any = None
        self.actor_spec: Optional[TaskSpec] = None
        self._actor_loop: Optional[Any] = None  # asyncio loop for async actors
        self._exec_pool = None

    # -- object access -----------------------------------------------------

    def get_serialized(self, oid: ObjectID,
                       timeout: Optional[float] = None) -> SerializedValue:
        """Local/shm store first; miss → pull from the daemon."""
        from raytpu.runtime.serialization import ZEROCOPY

        deadline = None if timeout is None else time.monotonic() + timeout
        delay = tuning.OBJECT_POLL_MIN_S
        while True:
            sv = self.store.try_get(oid)
            if sv is not None:
                return sv
            if ZEROCOPY:
                # Stream the daemon's copy straight into the SHARED shm
                # arena (both processes map it): chunks land in the final
                # region, and the retry try_get returns a pinned view —
                # the value never exists as a worker-heap blob. A create
                # collision (daemon landed it first) just falls back to
                # the heap receive inside begin_receive.
                try:
                    from raytpu.cluster.transfer import (
                        fetch_object as _stream_fetch,
                    )

                    if _stream_fetch(self.node, oid.hex(), self.store,
                                     timeout=tuning.WORKER_FETCH_TIMEOUT_S):
                        continue
                except Exception as e:
                    errors.swallow("worker.stream_fetch", e)
            # Whole-blob fallback; a daemon-side miss also kicks the
            # daemon's bounded cross-node pull.
            blob = self.node.call("fetch_object", oid.hex(),
                                  timeout=tuning.WORKER_FETCH_TIMEOUT_S)
            if blob is not None:
                return SerializedValue.from_buffer(blob)
            if deadline is not None and time.monotonic() >= deadline:
                from raytpu.core.errors import GetTimeoutError

                raise GetTimeoutError(f"object {oid.hex()} not ready")
            time.sleep(delay)
            delay = min(delay * 2, tuning.OBJECT_POLL_MAX_S)

    def collect_results(self, spec: TaskSpec) -> List[Tuple[bytes, Optional[bytes]]]:
        """Gather return values: ``(oid, None)`` = sealed in shared memory
        (daemon reads it there); ``(oid, blob)`` = ship inline."""
        out = []
        for oid in spec.return_ids():
            if self.store._shm is not None and self.store._shm.contains(oid):
                out.append((oid.binary(), None))
                continue
            sv = self.store.try_get(oid)
            if sv is not None:
                out.append((oid.binary(), sv.to_bytes()))
                # Shipped — drop the local heap copy.
                self.store.delete([oid])
        return out

    # -- execution ---------------------------------------------------------

    def collect_borrows(self, spec: TaskSpec) -> List[str]:
        """Argument refs still referenced after the task returned — the
        task (or actor state) kept a handle past its lifetime; the daemon
        reports them to the head BEFORE result locations, so the owner's
        free can never race the borrow (reference: borrows ride the
        PushTaskReply in ``task_manager.cc``).

        TOCTOU guard: another task thread may drop the last handle between
        our count read and the _borrowed.add — the out-of-scope hook then
        sees the oid absent and queues nothing. Re-checking the count
        AFTER the add closes that window: either we see zero and retract,
        or the hook sees the membership and queues the release (the head
        tolerates a release beating its borrow via early-release
        tombstones)."""
        from raytpu.runtime.task_spec import ArgKind

        rc = self.worker.reference_counter
        out: List[str] = []
        seen: set = set()
        cands = [ObjectRef.from_binary(rb).id for rb in spec.inline_refs]
        cands += [ObjectRef.from_binary(a.data).id for a in spec.args
                  if a.kind == ArgKind.REF]
        for oid in cands:
            if oid in seen:
                continue
            seen.add(oid)
            ref = rc.get(oid)
            if ref is None or ref.local_ref_count <= 0:
                continue
            with self._borrow_lock:
                if oid in self._borrowed:
                    continue
                self._borrowed.add(oid)
            ref = rc.get(oid)
            if ref is None or ref.local_ref_count <= 0:
                # Dropped mid-registration. Exactly one side wins the
                # discard under the lock: if we do, no release was queued
                # and the borrow is retracted silently; if the hook did,
                # the release is queued so the borrow MUST be reported
                # (the head cancels it via its early-release tombstone).
                with self._borrow_lock:
                    if oid in self._borrowed:
                        self._borrowed.discard(oid)
                        continue
            out.append(oid.hex())
        return out

    def execute_plain(self, spec: TaskSpec) -> dict:
        # kill_process here is the canonical "worker dies mid-task" chaos
        # scenario: the task was accepted but no result ever comes back.
        failpoint("worker.task.run")
        _tick_worker_task()
        if task_events.enabled():
            task_events.emit("task", spec.task_id.hex(),
                             task_events.TaskTransition.RUNNING,
                             name=spec.name, attempt=spec.attempt)
        # store_errors=False: the daemon owns retry policy — it stores the
        # error into the return slots only once retries are exhausted.
        err = self.worker.execute_task(spec, self.get_serialized,
                                       store_errors=False)
        if task_events.enabled():
            if err is None:
                task_events.emit("task", spec.task_id.hex(),
                                 task_events.TaskTransition.FINISHED,
                                 name=spec.name, attempt=spec.attempt)
            else:
                task_events.emit("task", spec.task_id.hex(),
                                 task_events.TaskTransition.FAILED,
                                 name=spec.name, attempt=spec.attempt,
                                 error=f"{type(err).__name__}: {err}"[:256])
        if task_events.ship_enabled():
            self.flush_task_events()
        return {"results": self.collect_results(spec),
                "borrows": self.collect_borrows(spec),
                "error": None if err is None else _dump_err(spec.name, err)}

    def flush_task_events(self) -> None:
        """Ship this worker's ring to the node daemon (which folds it into
        its own ring for the next heartbeat hop to the head). Requeued on
        failure so a transient daemon hiccup never loses events."""
        batch, dropped = task_events.drain()
        if not batch and not dropped:
            return
        try:
            self.node.notify("report_task_events", batch, dropped)
        except Exception:
            task_events.requeue(batch, dropped)

    def flush_metrics(self) -> None:
        """Ship this worker's metric delta frames to the node daemon
        (which relays them on its next heartbeat — same single ship path
        as task events). collect() rate-limits the registry snapshot;
        a failed notify requeues so frames survive a daemon hiccup."""
        metrics.collect(min_interval_s=tuning.METRICS_SHIP_PERIOD_S)
        frames, dropped = metrics.drain()
        if not frames and not dropped:
            return
        try:
            self.node.notify("report_metrics", frames, dropped)
        except Exception:
            metrics.requeue(frames, dropped)

    def flush_profile(self) -> None:
        """Ship this worker's continuous-profile snapshot frames to the
        node daemon (relayed on its next heartbeat — same single ship
        path as metrics). A failed notify requeues, so frames survive a
        daemon hiccup."""
        if profiler.profiling_enabled():
            frames, dropped = profiler.prof_drain()
            if not frames and not dropped:
                return
            try:
                self.node.notify("report_profile", frames, dropped)
            except Exception:
                profiler.prof_requeue(frames, dropped)

    def create_actor(self, spec: TaskSpec) -> dict:
        self.actor_spec = spec
        try:
            self.actor_instance = self.worker.create_actor_instance(
                spec, self.get_serialized)
            self.worker.put_serialized(
                spec.return_ids()[0], serialize(None),
                creating_task=spec.task_id)
            err = None
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, TaskError) else TaskError.from_exception(
                spec.name, e)
            self.worker._store_error(spec.return_ids(), spec, err)
        ac = spec.actor_creation
        if err is None and ac is not None and ac.is_async:
            import asyncio

            self._actor_loop = asyncio.new_event_loop()
            threading.Thread(target=self._actor_loop.run_forever,
                             name="actor-async-loop", daemon=True).start()
        return {"results": self.collect_results(spec),
                "borrows": self.collect_borrows(spec),
                "error": None if err is None else _dump_err(spec.name, err)}

    def execute_actor_task(self, spec: TaskSpec) -> dict:
        failpoint("worker.actor_task.run")
        _tick_worker_task()
        if self.actor_instance is None:
            err: BaseException = ActorDiedError(
                spec.actor_id.hex() if spec.actor_id else "?",
                "actor instance not created in this worker")
            self.worker._store_error(spec.return_ids(), spec, err)
            return {"results": self.collect_results(spec),
                    "error": _dump_err(spec.name, err)}
        if spec.runtime_env is None and self.actor_spec is not None:
            spec.runtime_env = self.actor_spec.runtime_env
        if self._actor_loop is not None:
            import asyncio

            fut = asyncio.run_coroutine_threadsafe(
                self._exec_async(spec), self._actor_loop)
            err = fut.result()
        else:
            err = self.worker.execute_task(
                spec, self.get_serialized, actor_instance=self.actor_instance)
        if task_events.enabled():
            task_events.emit(
                "task", spec.task_id.hex(),
                task_events.TaskTransition.FINISHED if err is None
                else task_events.TaskTransition.FAILED,
                name=spec.name, attempt=spec.attempt,
                error=None if err is None
                else f"{type(err).__name__}: {err}"[:256])
        if task_events.ship_enabled():
            self.flush_task_events()
        return {"results": self.collect_results(spec),
                "borrows": self.collect_borrows(spec),
                "error": None if err is None else _dump_err(spec.name, err)}

    async def actor_task_via_loop(self, spec: TaskSpec) -> dict:
        """Async-actor dispatch: runs as a coroutine on the worker's RPC
        server loop, forwarding to the actor's own event loop — no
        executor thread blocks on the result, so max_concurrency async
        calls can genuinely interleave (fixes the cross-call-signaling
        deadlock a thread-per-call bridge would have)."""
        import asyncio

        if spec.runtime_env is None and self.actor_spec is not None:
            spec.runtime_env = self.actor_spec.runtime_env
        if self.actor_instance is None or self._actor_loop is None:
            return self.execute_actor_task(spec)
        cf = asyncio.run_coroutine_threadsafe(
            self._exec_async(spec), self._actor_loop)
        err = await asyncio.wrap_future(cf)
        return {"results": self.collect_results(spec),
                "borrows": self.collect_borrows(spec),
                "error": None if err is None else _dump_err(spec.name, err)}

    async def _exec_async(self, spec: TaskSpec) -> Optional[BaseException]:
        """Async-actor method execution on the worker's event loop."""
        import inspect

        from raytpu.runtime import context as ctx_mod
        from raytpu.runtime_env import RuntimeEnvContext

        w = self.worker
        try:
            args, kwargs = w.resolve_args(spec, self.get_serialized)
            method = getattr(self.actor_instance, spec.method_name)
            ctx_mod.set_current(ctx_mod.RuntimeContext(
                job_id=w.job_id, node_id=w.node_id,
                task_id=spec.task_id, actor_id=spec.actor_id))
            with RuntimeEnvContext(spec.runtime_env):
                result = method(*args, **kwargs)
                if inspect.isawaitable(result):
                    result = await result
                if spec.streaming:
                    err = await w._run_stream_async(spec, result)
                    if err is not None:
                        w._store_error(spec.return_ids(), spec, err)
                    return err
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, TaskError) else TaskError.from_exception(
                spec.name, e)
            w._store_error(spec.return_ids(), spec, err)
            return err
        rids = spec.return_ids()
        if spec.num_returns == 1:
            w.put_serialized(rids[0], serialize(result),
                             creating_task=spec.task_id)
        else:
            for oid, v in zip(rids, list(result or [])):
                w.put_serialized(oid, serialize(v), creating_task=spec.task_id)
        return None


_worker_tasks_counter = None


def _tick_worker_task() -> None:
    """Per-worker task throughput, shipped with the metric pipeline so
    the head can break cluster tasks/s down by worker proc. Lazy: the
    counter registers on the first executed task, not at import."""
    global _worker_tasks_counter
    try:
        if _worker_tasks_counter is None:
            _worker_tasks_counter = metrics.Counter(
                "raytpu_worker_tasks_total",
                "tasks executed by the worker process")
        _worker_tasks_counter.inc()
    except Exception:  # pragma: no cover - metrics never fail a task
        pass


def main() -> None:  # pragma: no cover - runs as a subprocess
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--node", required=True, help="node daemon RPC address")
    ap.add_argument("--shm", default="", help="shared-memory store name")
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--job", required=True)
    ap.add_argument("--node-id", required=True)
    args = ap.parse_args()
    tracing.set_process_identity("worker", args.worker_id[:12])
    task_events.set_emitter_identity(node_id=args.node_id,
                                     worker_id=args.worker_id)
    metrics.set_shipper_identity(
        f"worker:{args.node_id[:12]}.{args.worker_id[:12]}")
    if profiler.profiling_enabled():
        profiler.start_continuous()

    host = _WorkerHost(
        args.node, args.shm or None,
        JobID.from_hex(args.job), NodeID.from_hex(args.node_id),
        args.worker_id,
    )

    # Route in-worker raytpu.* API calls through the daemon.
    from raytpu.runtime import api as _api

    backend = WorkerBackend(host)
    _api._backend = backend
    _api._worker = host.worker
    host.worker.put_object = _forwarding_put(host)
    host.worker.on_stream_element = _stream_forward(host)

    import asyncio

    server = RpcServer("127.0.0.1", 0)

    async def _offload(fn, *a):
        # Callers pass tracing.run_with_trace with tc already captured on
        # the loop thread (see h_execute below).
        return await asyncio.get_event_loop().run_in_executor(  # raytpulint: disable=RTP006
            None, fn, *a)

    def h_execute(peer: Peer, blob: bytes):
        # run_in_executor drops contextvars: capture the dispatch task's
        # trace context HERE and re-anchor it on the executor thread so
        # the execution span parents under the daemon's task.execute.
        tc = tracing.current_trace()
        return _offload(tracing.run_with_trace, tc, "worker.task.run",
                        host.execute_plain, wire.loads(blob))

    def h_create_actor(peer: Peer, blob: bytes):
        tc = tracing.current_trace()
        return _offload(tracing.run_with_trace, tc, "worker.actor.create",
                        host.create_actor, wire.loads(blob))

    def h_actor_task(peer: Peer, blob: bytes):
        spec = wire.loads(blob)
        if host._actor_loop is not None:
            return host.actor_task_via_loop(spec)
        tc = tracing.current_trace()
        return _offload(tracing.run_with_trace, tc, "worker.actor_task.run",
                        host.execute_actor_task, spec)

    def h_kill(peer: Peer, reason: str = ""):
        threading.Thread(target=_delayed_exit, daemon=True).start()
        return True

    def h_stream_ack(peer: Peer, task_id_hex: str, count: int):
        host.worker.stream_ack(TaskID.from_hex(task_id_hex), count)

    def h_stream_close(peer: Peer, task_id_hex: str, count: int):
        host.worker.stream_close(TaskID.from_hex(task_id_hex), count)

    server.register("execute", h_execute)
    server.register("create_actor", h_create_actor)
    server.register("actor_task", h_actor_task)
    server.register("stream_ack", h_stream_ack)
    server.register("stream_close", h_stream_close)
    server.register("kill", h_kill)
    server.register("ping", lambda peer: "pong")
    # Distributed tracing: the node daemon's trace_dump fan-in collects
    # this worker's span buffer (arming rides RAYTPU_TRACING in the env).
    server.register("trace_dump", lambda peer: tracing.dump())

    def h_stack(peer: Peer) -> str:
        from raytpu.util.stack_dump import dump_all_threads

        return dump_all_threads(
            header=f"worker {args.worker_id} pid={os.getpid()}")

    # Live profiling (reference: dashboard reporter's py-spy dump): the
    # RPC loop thread serves this even while task threads are busy.
    server.register("stack", h_stack)

    def h_profile(peer: Peer, duration_s: float = 2.0, hz: float = 50.0,
                  include_idle: bool = True):
        from raytpu.util.profiler import sample_for

        # Offloaded: the sampler blocks for duration_s and must not
        # stall the RPC loop (py-spy analogue: profile_manager.py:79).
        return _offload(sample_for, duration_s, hz, include_idle)

    server.register("profile", h_profile)

    def h_memory_profile(peer: Peer, duration_s: float = 2.0,
                         trace_frames: int = 16, top_n: int = 40,
                         stop_after: bool = False):
        from raytpu.util.memprofile import memory_profile

        # Offloaded like h_profile: the window sleeps for duration_s.
        return _offload(memory_profile, duration_s, trace_frames, top_n,
                        stop_after)

    server.register("memory_profile", h_memory_profile)
    addr = server.start()
    # kill_process here models a worker dying between exec and register —
    # the pool's spawn timeout / monitor reaps it.
    failpoint("worker.register.emit")
    host.node.call("register_worker", args.worker_id, addr, os.getpid())

    # Die with the daemon: if the control connection drops, exit.
    # Between liveness polls, ship any pending metric deltas to the
    # daemon (collect() rate-limits the snapshot; one flag check pins
    # the disabled cost of this loop).
    while not host.node.closed:
        time.sleep(tuning.PENDING_POLL_PERIOD_S)
        if metrics.enabled():
            host.flush_metrics()
        if profiler.profiling_enabled():
            host.flush_profile()
    os._exit(0)


def _delayed_exit() -> None:  # pragma: no cover
    # Let the kill reply flush before the hard exit.
    time.sleep(tuning.MONITOR_POLL_PERIOD_S)
    os._exit(0)


def _stream_forward(host: "_WorkerHost"):
    """Ship each stream element to the daemon the moment it is produced
    (the task's RPC reply is still in flight — elements must not wait for
    it). Shm-sealed elements just need a location report."""

    def fwd(oid: ObjectID) -> None:
        shm = host.store._shm
        if shm is not None and shm.contains(oid):
            host.node.notify("report_put", oid.hex())
            return
        sv = host.store.try_get(oid)
        if sv is not None:
            host.node.call("put_object", oid.hex(), sv.to_bytes())
            host.store.delete([oid])

    return fwd


def _forwarding_put(host: "_WorkerHost"):
    """``raytpu.put`` inside a worker: seal large values into shared memory
    (daemon sees them instantly), ship small ones to the daemon's heap
    store — either way the daemon can serve them as task args."""
    inner = host.worker.put_object

    def put(value, oid=None, creating_task=None, sv=None):
        ref = inner(value, oid=oid, creating_task=creating_task, sv=sv)
        shm = host.store._shm
        if shm is not None and shm.contains(ref.id):
            host.node.notify("report_put", ref.id.hex())
        else:
            sv2 = host.store.try_get(ref.id)
            if sv2 is not None:
                host.node.call("put_object", ref.id.hex(), sv2.to_bytes())
                host.store.delete([ref.id])
        return ref

    return put


if __name__ == "__main__":  # pragma: no cover
    main()
