"""Remote-driver proxy — the ``raytpu://`` endpoint.

Reference analogue: Ray Client (``python/ray/util/client/server/``,
``ray_client.proto``) — a driver outside the cluster network reaches ONE
public endpoint instead of the head plus every node. Ours is a frame
relay rather than a re-implementation of the API server: the driver's
:class:`~raytpu.cluster.client.ClusterBackend` runs unchanged on the
driver machine, but every RPC rides one proxy connection
(``relay_call(target, method, args)``); the proxy fans cluster pubsub
pushes back to each subscribed driver. Chunked object transfer works
through the same relay because the data plane is plain ``fetch_object_*``
calls (:mod:`raytpu.cluster.transfer`).

Targets are restricted to the head and addresses the head reports as
cluster nodes — the proxy is not an open TCP forwarder.
"""

from __future__ import annotations

import asyncio
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Set, Tuple

from raytpu.core.config import cfg
from raytpu.cluster.protocol import Peer, RpcClient, RpcServer
from raytpu.util import errors
from raytpu.util.resilience import current_deadline
from raytpu.util.tracing import current_trace

_NO_TIMEOUT = "__no_timeout__"  # legacy relay frames carry no timeout field


class DriverProxy:
    def __init__(self, head_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        self._head_address = head_address
        # The proxy is the one cluster surface a REMOTE driver reaches, so
        # it speaks the strict wire (wire.py contract): a pickle frame
        # from the network is rejected at decode instead of executing.
        # Driver payloads that genuinely carry code (task functions,
        # cloudpickled args) are opaque `bytes` inside relay frames and
        # deserialize only on cluster nodes, same trust shape as the
        # reference's ray:// client.
        self._rpc = RpcServer(host, port, allow_pickle=False)
        # Upstream calls are blocking (RpcClient.call); running them on the
        # server's asyncio loop thread would serialize every driver through
        # one thread and let a single hung upstream wedge the whole proxy
        # (ADVICE r3). Handlers therefore offload to this pool with a
        # finite timeout.
        self._pool = ThreadPoolExecutor(
            max_workers=32, thread_name_prefix="raytpu-proxy-relay")
        self._relay_timeout = float(cfg.proxy_relay_timeout_s)
        self._lock = threading.Lock()
        self._targets: Dict[str, RpcClient] = {}
        # (target, topic) -> driver peers to push to
        self._subs: Dict[Tuple[str, str], List[Peer]] = {}
        # target -> topics whose upstream subscription must be (re)wired —
        # a reconnected upstream RpcClient starts with no subscriptions.
        self._target_topics: Dict[str, Set[str]] = {}
        self._allowed: Set[str] = {head_address}
        self._rpc.register("proxy_info", self._proxy_info)
        self._rpc.register("relay_call", self._relay_call)
        self._rpc.register("relay_notify", self._relay_notify)
        self._rpc.on_disconnect(self._peer_gone)
        self.address: Optional[str] = None

    def start(self) -> str:
        self.address = self._rpc.start()
        # Fail fast if the head is unreachable.
        self._target(self._head_address).call("ping")
        return self.address

    def stop(self) -> None:
        with self._lock:
            clients = list(self._targets.values())
            self._targets.clear()
        for c in clients:
            c.close()
        self._rpc.stop()
        self._pool.shutdown(wait=False)

    # -- handlers ----------------------------------------------------------

    def _proxy_info(self, peer: Peer) -> dict:
        return {"head": self._head_address, "proxy": self.address}

    def _check_target(self, target: str) -> None:
        with self._lock:
            if target in self._allowed:
                return
        # Unknown target: refresh from the head every time — a node that
        # joined moments ago must be reachable immediately (the driver
        # learns of it via pubsub and routes to it right away).
        try:
            nodes = self._target(self._head_address).call("list_nodes")
            with self._lock:
                self._allowed = {self._head_address} | {
                    n["address"] for n in nodes if n.get("address")}
        except Exception as e:
            errors.swallow("proxy.refresh_allowed", e)
        with self._lock:
            if target not in self._allowed:
                raise PermissionError(
                    f"proxy: {target!r} is not a cluster address")

    def _target(self, address: str) -> RpcClient:
        with self._lock:
            c = self._targets.get(address)
            fresh = c is None or c.closed
            if fresh:
                c = self._targets[address] = RpcClient(address)
                topics = set(self._target_topics.get(address, ()))
            else:
                topics = ()
        # A fresh upstream connection carries no server-side subscriber
        # registration and no client-side callbacks: re-wire both for
        # every topic the drivers depend on.
        for topic in topics:
            try:
                c.subscribe(topic, self._make_fanout((address, topic)))
                c.call("subscribe", topic)
            except Exception as e:
                errors.swallow("proxy.rewire_subscription", e)
        return c

    def _make_fanout(self, key: Tuple[str, str]):
        def fanout(data, _key=key):
            with self._lock:
                targets = [p for p in self._subs.get(_key, ())
                           if not p.closed]
            for p in targets:
                p.push(_key[1], data)

        return fanout

    async def _relay_call(self, peer: Peer, target: str, method: str,
                          args: list, timeout: object = _NO_TIMEOUT):
        loop = asyncio.get_running_loop()
        # run_in_executor does NOT copy contextvars: the driver's deadline
        # and trace context (decoded into the dispatch task's context by
        # RpcServer) must be captured here, on the loop thread, and handed
        # through explicitly or they would die at this hop instead of
        # riding to the upstream.
        deadline = current_deadline()
        trace = current_trace()
        return await loop.run_in_executor(
            self._pool, self._relay_call_blocking, peer, target, method,
            args, timeout, deadline, trace)

    def _relay_call_blocking(self, peer: Peer, target: str, method: str,
                             args: list, timeout: object, deadline=None,
                             trace=None):
        self._check_target(target)
        if method == "subscribe":
            self._wire_subscription(peer, target, str(args[0]))
        # The driver's own budget bounds the upstream call. timeout=None
        # (e.g. a large put_object upload) maps to a long finite backstop
        # rather than forever, so a hung upstream releases its pool
        # thread eventually; legacy 4-arg frames get the default cap.
        if timeout is _NO_TIMEOUT:
            up: Optional[float] = self._relay_timeout
        elif timeout is None:
            up = max(self._relay_timeout, 3600.0)
        else:
            up = float(timeout)  # type: ignore[arg-type]
        return self._target(target).call(method, *args, timeout=up,
                                         deadline=deadline, trace=trace)

    async def _relay_notify(self, peer: Peer, target: str, method: str,
                            args: list) -> None:
        loop = asyncio.get_running_loop()
        # Notify frames are fire-and-forget and carry no trace context —
        # there is nothing to propagate across this hop.
        await loop.run_in_executor(  # raytpulint: disable=RTP006
            self._pool, self._relay_notify_blocking, target, method, args)

    def _relay_notify_blocking(self, target: str, method: str,
                               args: list) -> None:
        self._check_target(target)
        self._target(target).notify(method, *args)

    def _wire_subscription(self, peer: Peer, target: str,
                           topic: str) -> None:
        key = (target, topic)
        # Resolve the upstream client BEFORE recording the topic: a fresh
        # connection re-wires every topic already in _target_topics, so
        # recording first would make the subscribe below a duplicate
        # callback (client subscriptions append since the multi-waiter
        # change) and every push would fan out twice.
        client = self._target(target)
        with self._lock:
            first = key not in self._subs
            peers = self._subs.setdefault(key, [])
            if peer not in peers:
                peers.append(peer)
            self._target_topics.setdefault(target, set()).add(topic)
        if first:
            client.subscribe(topic, self._make_fanout(key))

    def _peer_gone(self, peer: Peer) -> None:
        with self._lock:
            for peers in self._subs.values():
                if peer in peers:
                    peers.remove(peer)


def main() -> None:
    import argparse
    import signal

    ap = argparse.ArgumentParser(description="raytpu remote-driver proxy")
    ap.add_argument("--head", required=True, help="head host:port")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=10001)
    args = ap.parse_args()
    proxy = DriverProxy(args.head, args.host, args.port)
    addr = proxy.start()
    print(f"raytpu driver proxy at raytpu://{addr} -> head {args.head}",
          flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    proxy.stop()


if __name__ == "__main__":
    main()
